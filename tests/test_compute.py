"""Compute-path tests on the 8-device CPU mesh: attention kernels, ring
attention vs reference, model forwards/training, mesh shardings."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from kubeshare_tpu.models import (
    MnistConfig,
    ResNetConfig,
    TransformerConfig,
    mnist_apply,
    mnist_init,
    resnet_apply,
    resnet_init,
    transformer_apply,
    transformer_apply_with_aux,
    transformer_init,
)
from kubeshare_tpu.models.transformer import (
    transformer_activation_spec,
    transformer_sharding_rules,
)
from kubeshare_tpu.ops import attention_reference, flash_attention, ring_attention
from kubeshare_tpu.ops.ring_attention import ring_attention_sharded
from kubeshare_tpu.ops.ulysses import ulysses_attention_sharded
from kubeshare_tpu.parallel import MeshSpec, batch_sharding, make_mesh
from kubeshare_tpu.parallel.mesh import shard_params
from kubeshare_tpu.parallel.train import TrainState, cross_entropy_loss, make_train_step


def rand(key, *shape):
    return jax.random.normal(jax.random.PRNGKey(key), shape, jnp.float32)


class TestAttention:
    def test_flash_matches_reference_interpret(self):
        q, k, v = (rand(i, 2, 4, 64, 16) for i in range(3))
        ref = attention_reference(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, block_q=32,
                              use_pallas=True, interpret=True)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=2e-4, atol=2e-4)

    def test_flash_non_causal(self):
        q, k, v = (rand(i, 1, 2, 32, 8) for i in range(3))
        ref = attention_reference(q, k, v, causal=False)
        out = flash_attention(q, k, v, causal=False, block_q=16,
                              use_pallas=True, interpret=True)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=2e-4, atol=2e-4)

    def test_flash_gradients(self):
        q, k, v = (rand(i, 1, 2, 32, 8) for i in range(3))

        def loss_flash(q, k, v):
            return flash_attention(q, k, v, use_pallas=True, interpret=True,
                                   block_q=16).sum()

        def loss_ref(q, k, v):
            return attention_reference(q, k, v).sum()

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_flash, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_cpu_auto_fallback(self):
        q, k, v = (rand(i, 1, 1, 16, 8) for i in range(3))
        out = flash_attention(q, k, v)  # auto: CPU -> reference
        ref = attention_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)

    def test_default_blocks_by_seq_len(self):
        """Seq-dependent kernel tiles (v5e sweep, docs/perf.md): larger
        blocks only at s >= 8192 AND only when they tile — an untiled
        pick would silently demote the call to the XLA reference."""
        from kubeshare_tpu.ops.attention import default_blocks

        assert default_blocks(2048) == (512, 1024)
        assert default_blocks(8192) == (1024, 2048)
        assert default_blocks(16384) == (1024, 2048)
        assert default_blocks(9216) == (512, 1024)  # 9216 % 2048 != 0


class TestBlockSparseAttention:
    """Arbitrary [n_qblocks, n_kblocks] masks over the flash kernels
    (document masking / prefix-LM / strided sparsity): the mask rides in
    SMEM and masked tiles are skipped in forward AND both backward
    sweeps."""

    BQ = BK = 16

    def _mask(self, nq, nk, seed=0, density=0.6):
        rng = np.random.default_rng(seed)
        mask = (rng.random((nq, nk)) < density).astype(np.int32)
        mask[0, 0] = 1  # at least one live tile
        return mask

    def test_matches_reference(self):
        from kubeshare_tpu.ops.attention import (block_sparse_attention,
                                                 block_sparse_reference)

        q, k, v = (rand(i, 2, 2, 64, 16) for i in range(3))
        mask = self._mask(4, 4)
        ref = block_sparse_reference(q, k, v, jnp.asarray(mask), True,
                                     self.BQ, self.BK)
        out = block_sparse_attention(q, k, v, mask, causal=True,
                                     block_q=self.BQ, block_k=self.BK,
                                     use_pallas=True, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_gradients_match_reference(self):
        from kubeshare_tpu.ops.attention import (block_sparse_attention,
                                                 block_sparse_reference)

        q, k, v = (rand(i, 1, 2, 32, 8) for i in range(3))
        mask = self._mask(2, 2, seed=1, density=0.8)

        def loss_kernel(q, k, v):
            return (block_sparse_attention(
                q, k, v, mask, causal=True, block_q=self.BQ,
                block_k=self.BK, use_pallas=True, interpret=True) ** 2).sum()

        def loss_ref(q, k, v):
            return (block_sparse_reference(
                q, k, v, jnp.asarray(mask), True, self.BQ, self.BK) ** 2).sum()

        g_kernel = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_kernel, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_gqa_heads_share_mask(self):
        from kubeshare_tpu.ops.attention import (block_sparse_attention,
                                                 block_sparse_reference)

        q = rand(0, 1, 4, 64, 16)
        k, v = (rand(i, 1, 2, 64, 16) for i in (1, 2))
        mask = self._mask(4, 4, seed=2, density=0.7)
        ref = block_sparse_reference(q, k, v, jnp.asarray(mask), True,
                                     self.BQ, self.BK)
        out = block_sparse_attention(q, k, v, mask, causal=True,
                                     block_q=self.BQ, block_k=self.BK,
                                     use_pallas=True, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_fully_masked_rows_zero(self):
        from kubeshare_tpu.ops.attention import block_sparse_attention

        q, k, v = (rand(i, 1, 1, 64, 8) for i in range(3))
        mask = np.ones((4, 4), np.int32)
        mask[2, :] = 0  # q-block 2 attends nothing
        out = block_sparse_attention(q, k, v, mask, causal=False,
                                     block_q=self.BQ, block_k=self.BK,
                                     use_pallas=True, interpret=True)
        rows = np.asarray(out)[:, :, 2 * self.BQ:3 * self.BQ, :]
        assert np.all(rows == 0)
        assert not np.any(np.isnan(np.asarray(out)))

    def test_mask_shape_validated(self):
        from kubeshare_tpu.ops.attention import block_sparse_attention

        q, k, v = (rand(i, 1, 1, 64, 8) for i in range(3))
        with pytest.raises(ValueError, match="block_mask shape"):
            block_sparse_attention(q, k, v, np.ones((3, 4), np.int32),
                                   block_q=self.BQ, block_k=self.BK,
                                   use_pallas=True, interpret=True)


class TestRingAttention:
    def test_matches_reference_over_mesh(self):
        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        b, h, s, d = 2, 2, 32, 8  # s=32 across sp=4 -> 8 per device
        q, k, v = (rand(i, b, h, s, d) for i in range(3))
        ref = attention_reference(q, k, v, causal=True)
        out = ring_attention_sharded(q, k, v, mesh, causal=True,
                                     batch_axis="dp", head_axis=None)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=2e-4, atol=2e-4)

    def test_non_causal(self):
        mesh = make_mesh(MeshSpec(dp=1, tp=1, sp=8))
        q, k, v = (rand(i, 1, 2, 64, 8) for i in range(3))
        ref = attention_reference(q, k, v, causal=False)
        out = ring_attention_sharded(q, k, v, mesh, causal=False,
                                     batch_axis=None, head_axis=None)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=2e-4, atol=2e-4)

    def test_grads_flow(self):
        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        q, k, v = (rand(i, 1, 1, 16, 4) for i in range(3))

        def loss(q):
            return ring_attention_sharded(q, k, v, mesh, batch_axis=None,
                                          head_axis=None).sum()

        g = jax.grad(loss)(q)
        assert np.isfinite(np.asarray(g)).all()


class TestZigzagRing:
    """Load-balanced causal ring (zigzag layout: each device holds one
    chunk from each end of the sequence, so every off-diagonal ring step
    is exactly half a block of unmasked work on every device)."""

    def test_permutation_round_trips(self):
        from kubeshare_tpu.ops.ring_attention import (
            zigzag_shard, zigzag_unshard)

        x = rand(0, 1, 1, 32, 4)
        back = zigzag_unshard(zigzag_shard(x, 4), 4)
        np.testing.assert_array_equal(np.asarray(x), np.asarray(back))
        # device 0's shard = first and last chunks of the global sequence
        z = zigzag_shard(x, 4)
        np.testing.assert_array_equal(np.asarray(z[:, :, :4]),
                                      np.asarray(x[:, :, :4]))
        np.testing.assert_array_equal(np.asarray(z[:, :, 4:8]),
                                      np.asarray(x[:, :, 28:]))

    def test_zigzag_matches_reference(self):
        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        b, h, s, d = 2, 2, 32, 8
        q, k, v = (rand(i, b, h, s, d) for i in range(3))
        ref = attention_reference(q, k, v, causal=True)
        out = ring_attention_sharded(q, k, v, mesh, causal=True,
                                     batch_axis="dp", head_axis=None,
                                     use_flash=False, layout="zigzag")
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=2e-4, atol=2e-4)

    def test_zigzag_hybrid_matches_reference(self):
        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        q, k, v = (rand(i, 2, 2, 64, 8) for i in range(3))
        ref = attention_reference(q, k, v, causal=True)
        out = ring_attention_sharded(q, k, v, mesh, causal=True,
                                     batch_axis="dp", head_axis=None,
                                     use_flash=True, interpret=True,
                                     layout="zigzag")
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=2e-4, atol=2e-4)

    def test_zigzag_gqa_matches_reference(self):
        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        q = rand(0, 2, 4, 32, 8)
        k, v = (rand(i, 2, 2, 32, 8) for i in (1, 2))
        ref = attention_reference(q, k, v, causal=True)
        out = ring_attention_sharded(q, k, v, mesh, causal=True,
                                     batch_axis="dp", head_axis=None,
                                     use_flash=False, layout="zigzag")
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=2e-4, atol=2e-4)

    def test_zigzag_grads_match_contiguous_ring(self):
        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        q, k, v = (rand(i, 1, 1, 16, 4) for i in range(3))

        def loss(fn_kwargs):
            def inner(q, k, v):
                return (ring_attention_sharded(
                    q, k, v, mesh, causal=True, batch_axis=None,
                    head_axis=None, **fn_kwargs) ** 2).sum()
            return inner

        g_ref = jax.grad(loss({"use_flash": False}), argnums=(0, 1, 2))(
            q, k, v)
        g_zz = jax.grad(
            loss({"use_flash": True, "interpret": True,
                  "layout": "zigzag"}), argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g_zz, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=2e-4, atol=2e-4)

    def test_zigzag_gqa_grads_match_dense_reference(self):
        """The hand-scheduled ring backward's grouped dk/dv reduction
        (query-head groups summing onto shared KV heads) must match dense
        autodiff."""
        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        q = rand(0, 2, 4, 32, 8)
        k, v = (rand(i, 2, 2, 32, 8) for i in (1, 2))

        def dense_loss(q, k, v):
            return (attention_reference(q, k, v, causal=True) ** 2).sum()

        def zz_loss(q, k, v):
            return (ring_attention_sharded(
                q, k, v, mesh, causal=True, batch_axis="dp",
                head_axis=None, use_flash=True, interpret=True,
                layout="zigzag") ** 2).sum()

        g_ref = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
        g_zz = jax.grad(zz_loss, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g_zz, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=5e-4, atol=5e-4)

    def test_zigzag_positions_cover_sequence(self):
        from kubeshare_tpu.ops.ring_attention import zigzag_positions

        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))

        def body():
            return zigzag_positions("sp", 8)

        pos = jax.shard_map(
            body, mesh=mesh, in_specs=(), out_specs=P("sp"),
        )()
        assert sorted(np.asarray(pos).tolist()) == list(range(32))

    def test_windowed_ring_matches_reference(self):
        """Sliding-window causal attention on the contiguous einsum ring:
        same band as the dense mask, including windows that cross shard
        boundaries (w not a multiple of the shard length)."""
        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        b, h, s, d = 2, 2, 32, 8
        q, k, v = (rand(i, b, h, s, d) for i in range(3))
        for window in (3, 8, 40):  # intra-shard, cross-shard, over-long
            ref = attention_reference(q, k, v, causal=True, window=window)
            out = ring_attention_sharded(q, k, v, mesh, causal=True,
                                         batch_axis="dp", head_axis=None,
                                         window=window)
            np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                       rtol=2e-4, atol=2e-4,
                                       err_msg=f"window={window}")

    def test_windowed_ring_grads_match_reference(self):
        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        q, k, v = (rand(i, 1, 1, 16, 4) for i in range(3))

        def ring_loss(q, k, v):
            return (ring_attention_sharded(
                q, k, v, mesh, causal=True, batch_axis=None, head_axis=None,
                window=5) ** 2).sum()

        def dense_loss(q, k, v):
            return (attention_reference(q, k, v, causal=True,
                                        window=5) ** 2).sum()

        g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
        g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g_ring, g_dense):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=2e-4, atol=2e-4)

    def test_windowed_ring_steps_math(self):
        from kubeshare_tpu.ops.ring_attention import windowed_ring_steps

        # window=1: each query sees only itself — no rotation at all
        assert windowed_ring_steps(1, 8, 8) == 1
        # a shard's FIRST query reaches window-1 back, so any window > 1
        # crosses into the previous shard
        assert windowed_ring_steps(8, 8, 8) == 2
        # reach-back w-1 <= s_local stays within ONE previous shard
        assert windowed_ring_steps(9, 8, 8) == 2
        assert windowed_ring_steps(10, 8, 8) == 3  # 9 back: two shards
        assert windowed_ring_steps(17, 8, 8) == 3
        # over-long windows clamp to the full ring
        assert windowed_ring_steps(1000, 8, 8) == 8

    def test_windowed_ring_comm_scales_with_window(self):
        """Skip-aware rotation (VERDICT r4 #6): the ring's rotation loop
        (and with it the K/V ppermute count) must truncate statically to
        the shards the band reaches — visible as the traced scan length —
        instead of always walking the whole ring."""
        import re
        from kubeshare_tpu.ops.ring_attention import windowed_ring_steps

        mesh = make_mesh(MeshSpec(dp=1, tp=1, sp=8))
        q, k, v = (rand(i, 1, 2, 64, 8) for i in range(3))  # s_local=8

        def scan_lengths(window):
            jaxpr = str(jax.make_jaxpr(
                lambda q, k, v: ring_attention_sharded(
                    q, k, v, mesh, causal=True, batch_axis=None,
                    head_axis=None, window=window, use_flash=False)
            )(q, k, v))
            return [int(m) for m in re.findall(r"length=(\d+)", jaxpr)]

        assert scan_lengths(None) == [7]       # full ring: sp-1 rotations
        for w in (4, 16, 63):
            expected = windowed_ring_steps(w, 8, 8) - 1
            assert scan_lengths(w) == [expected], f"window={w}"

    def test_windowed_ring_rejections(self):
        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        q, k, v = (rand(i, 1, 1, 16, 4) for i in range(3))
        with pytest.raises(ValueError, match="zigzag"):
            ring_attention_sharded(q, k, v, mesh, causal=True,
                                   batch_axis=None, head_axis=None,
                                   layout="zigzag", window=4)
        with pytest.raises(ValueError, match="einsum ring"):
            ring_attention_sharded(q, k, v, mesh, causal=True,
                                   batch_axis=None, head_axis=None,
                                   use_flash=True, window=4)
        with pytest.raises(ValueError, match="causal"):
            ring_attention_sharded(q, k, v, mesh, causal=False,
                                   batch_axis=None, head_axis=None,
                                   window=4)

    def test_zigzag_rejects_non_causal(self):
        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        q, k, v = (rand(i, 1, 1, 16, 4) for i in range(3))
        with pytest.raises(ValueError, match="causal"):
            ring_attention_sharded(q, k, v, mesh, causal=False,
                                   batch_axis=None, head_axis=None,
                                   layout="zigzag")

    def test_zigzag_balance_property(self):
        """The load-balance claim, asserted rather than narrated (VERDICT
        r3 #5): counting visible (unmasked) q-k pairs from the layout's own
        position invariant (_zigzag_shard_positions — the function the
        forward masks, backward, and RoPE all consume), every device does
        IDENTICAL work at every ring step — exactly half the 2c x 2c block
        off-diagonal — and per-device totals are exactly 1/sp of global
        causal work.  Contiguous shards fail the same count."""
        from kubeshare_tpu.ops.ring_attention import _zigzag_shard_positions

        sp, c = 4, 4
        pos = {
            i: np.asarray(_zigzag_shard_positions(i, sp, c))
            for i in range(sp)
        }

        def visible(qp, kp):
            return int((qp[:, None] >= kp[None, :]).sum())

        for t in range(1, sp):  # every off-diagonal ring step
            works = [visible(pos[i], pos[(i - t) % sp]) for i in range(sp)]
            assert len(set(works)) == 1, (t, works)
            assert works[0] == 2 * c * c  # exactly half the block

        diag = [visible(pos[i], pos[i]) for i in range(sp)]
        assert len(set(diag)) == 1
        s = 2 * c * sp
        per_device_total = diag[0] + (sp - 1) * 2 * c * c
        assert per_device_total * sp == s * (s + 1) // 2

        # contiguous layout: same count is imbalanced at every off-diagonal
        # step (some devices fully masked, others fully visible)
        cont = {i: np.arange(i * 2 * c, (i + 1) * 2 * c) for i in range(sp)}
        for t in range(1, sp):
            works = {visible(cont[i], cont[(i - t) % sp]) for i in range(sp)}
            assert len(works) > 1, t

    def test_zigzag_wrapper_counts_traced_calls(self):
        """The wrapper pays two global permutations per call; repeated
        calls under one trace (per-layer misuse) must be visible via the
        traced-call counter (ADVICE r3)."""
        import importlib

        # ops/__init__ re-exports a function named ring_attention, which
        # shadows the module for `import ... as` attribute lookup
        ra = importlib.import_module("kubeshare_tpu.ops.ring_attention")

        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        q, k, v = (rand(i, 1, 1, 16, 4) for i in range(3))
        before = ra.zigzag_traced_calls()

        @jax.jit
        def two_layers(q, k, v):
            o = ring_attention_sharded(q, k, v, mesh, causal=True,
                                       batch_axis=None, head_axis=None,
                                       use_flash=False, layout="zigzag")
            return ring_attention_sharded(o, k, v, mesh, causal=True,
                                          batch_axis=None, head_axis=None,
                                          use_flash=False, layout="zigzag")

        two_layers(q, k, v)
        assert ra.zigzag_traced_calls() >= before + 2


class TestRingFlashAttention:
    """Pallas-fused ring (VERDICT r1 #5): the flash kernel computes each
    ring step's block partial; interpret mode runs the real kernel on CPU."""

    def test_causal_matches_reference(self):
        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        b, h, s, d = 2, 2, 32, 8
        q, k, v = (rand(i, b, h, s, d) for i in range(3))
        ref = attention_reference(q, k, v, causal=True)
        out = ring_attention_sharded(q, k, v, mesh, causal=True,
                                     batch_axis="dp", head_axis=None,
                                     use_flash=True, interpret=True)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=2e-4, atol=2e-4)

    def test_non_causal_matches_reference(self):
        mesh = make_mesh(MeshSpec(dp=1, tp=1, sp=8))
        q, k, v = (rand(i, 1, 2, 64, 8) for i in range(3))
        ref = attention_reference(q, k, v, causal=False)
        out = ring_attention_sharded(q, k, v, mesh, causal=False,
                                     batch_axis=None, head_axis=None,
                                     use_flash=True, interpret=True)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=2e-4, atol=2e-4)

    def test_matches_einsum_ring(self):
        mesh = make_mesh(MeshSpec(dp=1, tp=2, sp=4))
        q, k, v = (rand(i, 1, 2, 32, 8) for i in range(3))
        einsum_out = ring_attention_sharded(q, k, v, mesh, causal=True,
                                            batch_axis=None, head_axis="tp",
                                            use_flash=False)
        flash_out = ring_attention_sharded(q, k, v, mesh, causal=True,
                                           batch_axis=None, head_axis="tp",
                                           use_flash=True, interpret=True)
        np.testing.assert_allclose(np.asarray(einsum_out),
                                   np.asarray(flash_out),
                                   rtol=2e-4, atol=2e-4)

    def test_grads_match_einsum_ring(self):
        """The custom-vjp backward (einsum-ring recompute) must produce the
        einsum path's exact gradients."""
        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        q, k, v = (rand(i, 1, 1, 16, 4) for i in range(3))

        def loss(fn_kwargs, q, k, v):
            return (ring_attention_sharded(
                q, k, v, mesh, batch_axis=None, head_axis=None, **fn_kwargs
            ) ** 2).sum()

        g_ref = jax.grad(loss, argnums=(1, 2, 3))({"use_flash": False}, q, k, v)
        g_flash = jax.grad(loss, argnums=(1, 2, 3))(
            {"use_flash": True, "interpret": True}, q, k, v
        )
        for a, b in zip(g_ref, g_flash):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)


class TestModels:
    def test_mnist_forward_and_train(self):
        config = MnistConfig()
        params = mnist_init(jax.random.PRNGKey(0), config)
        images = rand(1, 8, 28, 28, 1)
        logits = mnist_apply(params, images)
        assert logits.shape == (8, 10)

        init_state, train_step = make_train_step(
            mnist_apply,
            loss_fn=lambda logits, y: cross_entropy_loss(logits, y),
        )
        state = init_state(params)
        labels = jnp.zeros((8,), jnp.int32)
        losses = []
        for _ in range(5):
            state, loss = train_step(state, images, labels)
            losses.append(float(loss))
        assert losses[-1] < losses[0]  # it learns the constant label

    def test_resnet_forward(self):
        config = ResNetConfig(widths=(8, 16), blocks_per_stage=(1, 1))
        params = resnet_init(jax.random.PRNGKey(0), config)
        logits = resnet_apply(params, rand(1, 4, 32, 32, 3), config)
        assert logits.shape == (4, 10)
        assert np.isfinite(np.asarray(logits)).all()

    def test_transformer_forward(self):
        config = TransformerConfig(
            vocab_size=128, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq_len=64, dtype=jnp.float32, attention="reference",
        )
        params = transformer_init(jax.random.PRNGKey(0), config)
        tokens = jnp.zeros((2, 16), jnp.int32)
        logits = transformer_apply(params, tokens, config)
        assert logits.shape == (2, 16, 128)
        assert np.isfinite(np.asarray(logits)).all()


class TestShardedTraining:
    def test_transformer_dp_tp_training(self):
        mesh = make_mesh(MeshSpec(dp=2, tp=2, sp=2))
        config = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq_len=32, dtype=jnp.float32, attention="reference",
        )
        params = transformer_init(jax.random.PRNGKey(0), config)
        rules = transformer_sharding_rules()
        init_state, train_step = make_train_step(
            lambda p, x: transformer_apply(p, x, config),
            mesh=mesh,
            param_rules=rules,
        )
        state = init_state(params)
        # embed sharded over tp
        embed_sharding = state.params["embed"].sharding
        assert embed_sharding.spec == P("tp", None)

        tokens = jax.device_put(
            jnp.ones((4, 16), jnp.int32),
            batch_sharding(mesh, ndim=2),
        )
        targets = jax.device_put(
            jnp.ones((4, 16), jnp.int32),
            batch_sharding(mesh, ndim=2),
        )
        losses = []
        for _ in range(3):
            state, loss = train_step(state, tokens, targets)
            losses.append(float(loss))
        assert losses[-1] < losses[0]
        assert int(state.step) == 3

    def test_fsdp_rules_match_replicated_training(self):
        """Zero-style parameter sharding (transformer_fsdp_rules): params
        AND optimizer moments shard over dp, and the training trajectory
        is numerically the computation the replicated rules run."""
        from kubeshare_tpu.models.transformer import transformer_fsdp_rules

        mesh = make_mesh(MeshSpec(dp=2, tp=2, sp=2))
        config = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq_len=32, dtype=jnp.float32, attention="reference",
        )
        params = transformer_init(jax.random.PRNGKey(0), config)
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64),
            batch_sharding(mesh, ndim=2))

        losses = {}
        for name, rules in (("base", transformer_sharding_rules()),
                            ("fsdp", transformer_fsdp_rules())):
            init_state, train_step = make_train_step(
                lambda p, x: transformer_apply(p, x, config),
                mesh=mesh, param_rules=rules, donate_state=False,
            )
            state = init_state(params)
            if name == "fsdp":
                # weights and adam moments actually shard over dp
                assert state.params["embed"].sharding.spec == P("tp", "dp")
                wq = state.params["layers"][0]["attn"]["wq"]
                assert wq.sharding.spec == P("dp", "tp", None)
                moment = state.opt_state[0].mu["layers"][0]["attn"]["wq"]
                assert moment.sharding.spec == P("dp", "tp", None)
            run = []
            for _ in range(2):
                state, loss = train_step(state, tokens, tokens)
                run.append(float(loss))
            losses[name] = run
        np.testing.assert_allclose(losses["fsdp"], losses["base"],
                                   rtol=2e-5, atol=2e-6)

    def test_mesh_spec_resolution(self):
        assert MeshSpec(dp=-1, tp=2, sp=2).resolve(8) == (2, 1, 2, 2)
        assert MeshSpec(dp=8, tp=1, sp=1).resolve(8) == (8, 1, 1, 1)
        assert MeshSpec(dp=-1, ep=2, tp=2).resolve(8) == (2, 2, 2, 1)
        with pytest.raises(ValueError):
            MeshSpec(dp=3, tp=1, sp=1).resolve(8)

    def test_mesh_axes_with_and_without_ep(self):
        # ep == 1 keeps the historical three-axis shape (sharding rules
        # that name only dp/tp/sp keep working unchanged)
        assert make_mesh(MeshSpec(dp=2, tp=2, sp=2)).axis_names == (
            "dp", "tp", "sp")
        mesh = make_mesh(MeshSpec(dp=2, ep=2, tp=2))
        assert mesh.axis_names == ("dp", "ep", "tp", "sp")
        assert mesh.shape["ep"] == 2
        # batch axis spans dp x ep so every device holds a batch shard
        assert batch_sharding(mesh).spec == P(("dp", "ep"), None)

    def test_shard_params_rules(self):
        mesh = make_mesh(MeshSpec(dp=2, tp=2, sp=2))
        params = {"attn": {"wq": jnp.ones((8, 4, 2))}, "norm": jnp.ones((4,))}
        placed = shard_params(params, {"wq": P(None, "tp", None)}, mesh)
        assert placed["attn"]["wq"].sharding.spec == P(None, "tp", None)
        assert placed["norm"].sharding.spec == P()


class TestRingTransformer:
    def test_ring_forward_matches_dense(self):
        from kubeshare_tpu.models.transformer import transformer_apply_ring

        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        config = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq_len=64, dtype=jnp.float32, attention="reference",
        )
        params = transformer_init(jax.random.PRNGKey(0), config)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64)
        dense = transformer_apply(params, tokens, config)
        ring = transformer_apply_ring(params, tokens, config, mesh)
        np.testing.assert_allclose(np.asarray(dense), np.asarray(ring),
                                   rtol=2e-4, atol=2e-4)

    def test_windowed_ring_forward_matches_dense(self):
        """A sliding-window model through the sequence-parallel ring must
        match its own dense forward (the band the dense mask keeps)."""
        from kubeshare_tpu.models.transformer import transformer_apply_ring

        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        config = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq_len=64, dtype=jnp.float32, attention="reference",
            attention_window=6,
        )
        params = transformer_init(jax.random.PRNGKey(0), config)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64)
        dense = transformer_apply(params, tokens, config)
        ring = transformer_apply_ring(params, tokens, config, mesh)
        np.testing.assert_allclose(np.asarray(dense), np.asarray(ring),
                                   rtol=2e-4, atol=2e-4)

    def test_gqa_ring_forward_matches_dense(self):
        """A GQA model (2 KV heads under 4 query heads) through the
        sequence-parallel ring must match its own dense forward — the
        model-level closure of the op-level GQA ring tests."""
        from kubeshare_tpu.models.transformer import transformer_apply_ring

        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        config = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_kv_heads=2, n_layers=2,
            d_ff=64, max_seq_len=64, dtype=jnp.float32,
            attention="reference", positional="rope",
        )
        params = transformer_init(jax.random.PRNGKey(0), config)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64)
        dense = transformer_apply(params, tokens, config)
        ring = transformer_apply_ring(params, tokens, config, mesh)
        np.testing.assert_allclose(np.asarray(dense), np.asarray(ring),
                                   rtol=2e-4, atol=2e-4)

    def test_ring_flash_forward_matches_dense(self):
        """Model-level: the Pallas-fused ring body (interpret mode) must
        reproduce the dense forward bit-for-tolerance."""
        from kubeshare_tpu.models.transformer import transformer_apply_ring

        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        config = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq_len=64, dtype=jnp.float32, attention="reference",
        )
        params = transformer_init(jax.random.PRNGKey(0), config)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64)
        dense = transformer_apply(params, tokens, config)
        ring = transformer_apply_ring(params, tokens, config, mesh,
                                      use_flash=True, interpret=True)
        np.testing.assert_allclose(np.asarray(dense), np.asarray(ring),
                                   rtol=2e-4, atol=2e-4)

    @pytest.mark.parametrize("positional", ["rope", "learned"])
    def test_zigzag_ring_forward_matches_dense(self, positional):
        """End-to-end zigzag: tokens permuted once, every layer attends
        with the balanced ring and positions follow the permutation
        (RoPE and the learned table), logits permuted back."""
        from kubeshare_tpu.models.transformer import transformer_apply_ring

        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        config = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq_len=64, dtype=jnp.float32, attention="reference",
            positional=positional,
        )
        params = transformer_init(jax.random.PRNGKey(0), config)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64)
        dense = transformer_apply(params, tokens, config)
        ring = transformer_apply_ring(params, tokens, config, mesh,
                                      layout="zigzag", use_flash=False)
        np.testing.assert_allclose(np.asarray(dense), np.asarray(ring),
                                   rtol=2e-4, atol=2e-4)

    def test_zigzag_ring_flash_forward_matches_dense(self):
        from kubeshare_tpu.models.transformer import transformer_apply_ring

        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        config = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq_len=64, dtype=jnp.float32, attention="reference",
            positional="rope",
        )
        params = transformer_init(jax.random.PRNGKey(0), config)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64)
        dense = transformer_apply(params, tokens, config)
        ring = transformer_apply_ring(params, tokens, config, mesh,
                                      layout="zigzag", use_flash=True,
                                      interpret=True)
        np.testing.assert_allclose(np.asarray(dense), np.asarray(ring),
                                   rtol=2e-4, atol=2e-4)

    def test_ring_config_on_dense_entry_raises(self):
        config = TransformerConfig(attention="ring")
        params_cfg = TransformerConfig(
            vocab_size=8, d_model=8, n_heads=2, n_layers=1, d_ff=8,
            max_seq_len=8, dtype=jnp.float32, attention="ring",
        )
        params = transformer_init(jax.random.PRNGKey(0), params_cfg)
        with pytest.raises(ValueError):
            transformer_apply(params, jnp.zeros((1, 8), jnp.int32), params_cfg)


class TestUlyssesAttention:
    """All-to-all (Ulysses-style) sequence parallelism (ops/ulysses.py):
    two all_to_all collectives swap seq-sharding for head-sharding, full
    local attention, swap back."""

    def test_causal_matches_reference(self):
        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        b, h, s, d = 2, 4, 32, 8  # h=4 divisible by sp=4
        q, k, v = (rand(i, b, h, s, d) for i in range(3))
        ref = attention_reference(q, k, v, causal=True)
        out = ulysses_attention_sharded(q, k, v, mesh, causal=True,
                                        batch_axis="dp", head_axis=None)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=2e-4, atol=2e-4)

    def test_non_causal_matches_reference(self):
        mesh = make_mesh(MeshSpec(dp=1, tp=1, sp=8))
        q, k, v = (rand(i, 1, 8, 64, 8) for i in range(3))
        ref = attention_reference(q, k, v, causal=False)
        out = ulysses_attention_sharded(q, k, v, mesh, causal=False,
                                        batch_axis=None, head_axis=None)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=2e-4, atol=2e-4)

    def test_windowed_matches_reference(self):
        """Sliding-window attention composes with Ulysses (it cannot with
        the ring — K/V visibility there is ring-position-dependent)."""
        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        q, k, v = (rand(i, 1, 4, 32, 8) for i in range(3))
        ref = attention_reference(q, k, v, causal=True, window=8)
        out = ulysses_attention_sharded(q, k, v, mesh, causal=True, window=8,
                                        batch_axis=None, head_axis=None)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=2e-4, atol=2e-4)

    def test_flash_kernel_body(self):
        """Interpret mode runs the real Pallas kernel on the swapped
        (full-sequence) shards."""
        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        q, k, v = (rand(i, 2, 4, 32, 8) for i in range(3))
        ref = attention_reference(q, k, v, causal=True)
        out = ulysses_attention_sharded(q, k, v, mesh, causal=True,
                                        batch_axis="dp", head_axis=None,
                                        use_flash=True, interpret=True)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=2e-4, atol=2e-4)

    def test_grads_flow(self):
        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        q, k, v = (rand(i, 1, 4, 16, 4) for i in range(3))

        def loss(q):
            return ulysses_attention_sharded(q, k, v, mesh, batch_axis=None,
                                             head_axis=None).sum()

        g = jax.grad(loss)(q)
        assert np.isfinite(np.asarray(g)).all()
        # the collective transposes to the mirrored all_to_all: a reference
        # gradient check pins the values, not just finiteness
        ref_g = jax.grad(
            lambda q: attention_reference(q, k, v, causal=True).sum()
        )(q)
        np.testing.assert_allclose(np.asarray(ref_g), np.asarray(g),
                                   rtol=2e-4, atol=2e-4)

    def test_heads_not_divisible_raises(self):
        mesh = make_mesh(MeshSpec(dp=1, tp=1, sp=8))
        q, k, v = (rand(i, 1, 4, 32, 8) for i in range(3))  # 4 heads, sp=8
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention_sharded(q, k, v, mesh, batch_axis=None,
                                      head_axis=None)

    def test_composes_with_tp(self):
        """Heads split over tp first; the sp swap works on the tp-local
        head group."""
        mesh = make_mesh(MeshSpec(dp=1, tp=2, sp=4))
        q, k, v = (rand(i, 1, 8, 32, 8) for i in range(3))  # 8/tp2 = 4, sp=4
        ref = attention_reference(q, k, v, causal=True)
        out = ulysses_attention_sharded(q, k, v, mesh, causal=True,
                                        batch_axis=None, head_axis="tp")
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=2e-4, atol=2e-4)


class TestGQASequenceParallel:
    """Grouped-query attention through both sequence-parallel paths: K/V
    stay at their small head width on the wire (ring rotation / all_to_all);
    only the block math expands per group."""

    def _gqa(self, h=4, h_kv=2, s=32, d=8):
        q = rand(0, 2, h, s, d)
        k = rand(1, 2, h_kv, s, d)
        v = rand(2, 2, h_kv, s, d)
        return q, k, v

    def test_ring_einsum_gqa_matches_reference(self):
        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        q, k, v = self._gqa()
        ref = attention_reference(q, k, v, causal=True)
        out = ring_attention_sharded(q, k, v, mesh, causal=True,
                                     batch_axis="dp", head_axis=None,
                                     use_flash=False)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=2e-4, atol=2e-4)

    def test_ring_flash_gqa_matches_reference(self):
        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        q, k, v = self._gqa()
        ref = attention_reference(q, k, v, causal=True)
        out = ring_attention_sharded(q, k, v, mesh, causal=True,
                                     batch_axis="dp", head_axis=None,
                                     use_flash=True, interpret=True)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=2e-4, atol=2e-4)

    def test_ring_gqa_grads_match_reference(self):
        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        q, k, v = self._gqa(s=16)

        def loss_ring(q, k, v):
            return ring_attention_sharded(q, k, v, mesh, batch_axis="dp",
                                          head_axis=None,
                                          use_flash=False).sum()

        def loss_ref(q, k, v):
            return attention_reference(q, k, v, causal=True).sum()

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ring, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)

    def test_ulysses_gqa_matches_reference(self):
        mesh = make_mesh(MeshSpec(dp=4, tp=1, sp=2))
        q, k, v = self._gqa()  # h=4, h_kv=2: both divisible by sp=2
        ref = attention_reference(q, k, v, causal=True)
        out = ulysses_attention_sharded(q, k, v, mesh, causal=True,
                                        batch_axis=None, head_axis=None)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=2e-4, atol=2e-4)

    def test_ulysses_kv_heads_not_divisible_raises(self):
        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        q, k, v = self._gqa()  # h_kv=2 not divisible by sp=4
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention_sharded(q, k, v, mesh, batch_axis="dp",
                                      head_axis=None)

    def test_ring_uneven_heads_raises(self):
        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        q = rand(0, 2, 3, 32, 8)
        k = rand(1, 2, 2, 32, 8)
        with pytest.raises(ValueError, match="multiple"):
            ring_attention_sharded(q, k, k, mesh, batch_axis="dp",
                                   head_axis=None, use_flash=False)


class TestUlyssesTransformer:
    def test_forward_matches_dense(self):
        from kubeshare_tpu.models.transformer import transformer_apply_ulysses

        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        config = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq_len=64, dtype=jnp.float32, attention="reference",
        )
        params = transformer_init(jax.random.PRNGKey(0), config)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64)
        dense = transformer_apply(params, tokens, config)
        out = transformer_apply_ulysses(params, tokens, config, mesh)
        np.testing.assert_allclose(np.asarray(dense), np.asarray(out),
                                   rtol=2e-4, atol=2e-4)

    def test_windowed_forward_matches_dense(self):
        from kubeshare_tpu.models.transformer import transformer_apply_ulysses

        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        config = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq_len=64, dtype=jnp.float32, attention="reference",
            attention_window=8,
        )
        params = transformer_init(jax.random.PRNGKey(0), config)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64)
        dense = transformer_apply(params, tokens, config)
        out = transformer_apply_ulysses(params, tokens, config, mesh)
        np.testing.assert_allclose(np.asarray(dense), np.asarray(out),
                                   rtol=2e-4, atol=2e-4)

    def test_indivisible_heads_raises(self):
        from kubeshare_tpu.models.transformer import transformer_apply_ulysses

        mesh = make_mesh(MeshSpec(dp=1, tp=1, sp=8))
        config = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=1, d_ff=64,
            max_seq_len=64, dtype=jnp.float32, attention="reference",
        )
        params = transformer_init(jax.random.PRNGKey(0), config)
        tokens = jnp.zeros((1, 32), jnp.int32)
        with pytest.raises(ValueError, match="divisible"):
            transformer_apply_ulysses(params, tokens, config, mesh)

    def test_ulysses_config_on_dense_entry_raises(self):
        cfg = TransformerConfig(
            vocab_size=8, d_model=8, n_heads=2, n_layers=1, d_ff=8,
            max_seq_len=8, dtype=jnp.float32, attention="ulysses",
        )
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        with pytest.raises(ValueError):
            transformer_apply(params, jnp.zeros((1, 8), jnp.int32), cfg)


class TestDecoding:
    def _setup(self):
        from kubeshare_tpu.models.transformer import TransformerConfig, transformer_init

        config = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq_len=32, dtype=jnp.float32, attention="reference",
        )
        params = transformer_init(jax.random.PRNGKey(0), config)
        return config, params

    def test_incremental_matches_full_forward(self):
        # the incremental path explicitly: bulk prefill IS the dense
        # forward, so comparing it to dense would be a tautology
        from kubeshare_tpu.models.decoding import (
            prefill_incremental as prefill)

        config, params = self._setup()
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 10), 0, 64)
        # cached incremental prefill must equal the dense forward's last step
        dense = transformer_apply(params, prompt, config)
        _, last_logits = prefill(params, config, prompt)
        np.testing.assert_allclose(
            np.asarray(dense[:, -1]), np.asarray(last_logits),
            rtol=2e-4, atol=2e-4,
        )

    def test_gqa_incremental_matches_full_forward(self):
        """GQA decode: the grouped cached-attention path (KV cache holds
        n_kv_heads, query heads grouped over it with no materialized
        repetition) must equal the dense GQA forward."""
        from kubeshare_tpu.models.decoding import (
            init_kv_cache, prefill_incremental as prefill)
        from kubeshare_tpu.models.transformer import (
            TransformerConfig, transformer_init)

        config = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_kv_heads=2, n_layers=2,
            d_ff=64, max_seq_len=32, dtype=jnp.float32,
            attention="reference", positional="rope",
        )
        params = transformer_init(jax.random.PRNGKey(0), config)
        # the cache — decode's dominant HBM cost — holds kv heads only
        assert init_kv_cache(config, 2)["k"].shape == (2, 2, 2, 32, 8)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 10), 0, 64)
        dense = transformer_apply(params, prompt, config)
        _, last_logits = prefill(params, config, prompt)
        np.testing.assert_allclose(
            np.asarray(dense[:, -1]), np.asarray(last_logits),
            rtol=2e-4, atol=2e-4,
        )

    def test_bulk_prefill_matches_incremental(self):
        """The bulk prefill (one dense forward + bulk cache fill) must
        produce the same cache and logits as the token-at-a-time oracle —
        for MHA, GQA, and a MoE config (whose expert buffers prefill pins
        to the token count so routing stays position/batch-independent)."""
        from kubeshare_tpu.models.decoding import (
            greedy_decode, prefill, prefill_incremental)
        from kubeshare_tpu.models.transformer import (
            TransformerConfig, transformer_init)

        cases = {
            "mha": dict(),
            "gqa_rope": dict(n_kv_heads=2, positional="rope"),
            "moe": dict(moe_every=2, moe_num_experts=4, moe_top_k=2),
            "windowed": dict(attention_window=6),
        }
        for name, extra in cases.items():
            config = TransformerConfig(
                vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
                max_seq_len=32, dtype=jnp.float32, attention="reference",
                **extra)
            params = transformer_init(jax.random.PRNGKey(0), config)
            prompt = jax.random.randint(
                jax.random.PRNGKey(1), (2, 10), 0, 64)
            cache_b, logits_b = prefill(params, config, prompt)
            cache_i, logits_i = prefill_incremental(params, config, prompt)
            np.testing.assert_allclose(
                np.asarray(logits_b), np.asarray(logits_i),
                rtol=2e-4, atol=2e-4, err_msg=name)
            assert int(cache_b["length"]) == int(cache_i["length"]) == 10
            np.testing.assert_allclose(
                np.asarray(cache_b["k"]), np.asarray(cache_i["k"]),
                rtol=2e-4, atol=2e-4, err_msg=name)
            np.testing.assert_allclose(
                np.asarray(cache_b["v"]), np.asarray(cache_i["v"]),
                rtol=2e-4, atol=2e-4, err_msg=name)
            # and the next decode step computes identical logits from
            # either cache
            from kubeshare_tpu.models.decoding import _decode_one

            token = jnp.argmax(logits_b, axis=-1).astype(jnp.int32)
            step_b, _ = _decode_one(params, config, cache_b, token)
            step_i, _ = _decode_one(params, config, cache_i, token)
            np.testing.assert_allclose(
                np.asarray(step_b), np.asarray(step_i),
                rtol=2e-4, atol=2e-4, err_msg=name)
            out = greedy_decode(params, config, prompt, 4)
            assert out.shape == (2, 4)

    def test_chunked_prefill_matches_bulk(self):
        """Chunked prefill (O(chunk) activations per step) must produce
        the same cache and logits as the bulk dense pass — across
        MHA/GQA/MoE/windowed configs and chunk sizes incl. chunk=1 (which
        is exactly the incremental path) and chunk=prompt_len."""
        from kubeshare_tpu.models.decoding import prefill, prefill_chunked
        from kubeshare_tpu.models.transformer import (
            TransformerConfig, transformer_init)

        cases = {
            "mha": dict(),
            "gqa_rope": dict(n_kv_heads=2, positional="rope"),
            "moe": dict(moe_every=2, moe_num_experts=4, moe_top_k=2),
            "windowed": dict(attention_window=6),
        }
        for name, extra in cases.items():
            config = TransformerConfig(
                vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
                max_seq_len=32, dtype=jnp.float32, attention="reference",
                **extra)
            params = transformer_init(jax.random.PRNGKey(0), config)
            prompt = jax.random.randint(
                jax.random.PRNGKey(1), (2, 12), 0, 64)
            cache_b, logits_b = prefill(params, config, prompt)
            for chunk in (1, 4, 12):
                cache_c, logits_c = prefill_chunked(
                    params, config, prompt, chunk)
                np.testing.assert_allclose(
                    np.asarray(logits_c), np.asarray(logits_b),
                    rtol=2e-4, atol=2e-4, err_msg=f"{name} chunk={chunk}")
                np.testing.assert_allclose(
                    np.asarray(cache_c["k"]), np.asarray(cache_b["k"]),
                    rtol=2e-4, atol=2e-4, err_msg=f"{name} chunk={chunk}")
                np.testing.assert_allclose(
                    np.asarray(cache_c["v"]), np.asarray(cache_b["v"]),
                    rtol=2e-4, atol=2e-4, err_msg=f"{name} chunk={chunk}")
                assert int(cache_c["length"]) == 12

    def test_decode_from_chunked_cache_matches_greedy(self):
        """The serving split — chunked prefill + greedy_decode_with_cache
        — must emit the same tokens as the one-shot greedy_decode."""
        from kubeshare_tpu.models.decoding import (
            greedy_decode, greedy_decode_with_cache, prefill_chunked)
        from kubeshare_tpu.models.transformer import (
            TransformerConfig, transformer_init)

        config = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_kv_heads=2, n_layers=2,
            d_ff=64, max_seq_len=32, dtype=jnp.float32,
            attention="reference", positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, 64)
        one_shot = greedy_decode(params, config, prompt, 8)
        cache, logits = prefill_chunked(params, config, prompt, 4)
        split = greedy_decode_with_cache(params, config, cache, logits, 8)
        np.testing.assert_array_equal(np.asarray(one_shot),
                                      np.asarray(split))
        # the split path keeps the one-shot path's loud overflow failure
        with pytest.raises(ValueError, match="capacity"):
            greedy_decode_with_cache(params, config, cache, logits, 32)
        # zero/negative generation lengths fail loudly too (ADVICE r4)
        with pytest.raises(ValueError, match="max_new_tokens"):
            greedy_decode_with_cache(params, config, cache, logits, 0)

    def test_jitted_continuation_overflow_caught_with_static_prefill(self):
        """ADVICE r4 (medium): under jit the cache length is traced, so
        the capacity bound can only bind through the static
        ``prefill_length`` — a jitted continuation from a nearly-full
        cache must fail at trace time, not clamp-overwrite the last
        slot."""
        from kubeshare_tpu.models.decoding import (
            greedy_decode_with_cache, prefill)
        from kubeshare_tpu.models.transformer import (
            TransformerConfig, transformer_init)

        config = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq_len=16, dtype=jnp.float32, attention="reference",
            positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 12), 0, 64)
        cache, logits = prefill(params, config, prompt)

        # 12 prefilled + 8 > 16: the jitted serving pattern
        # (examples/serve_fractional.py) with the static prefill length
        decode_fn = jax.jit(
            lambda c, lg: greedy_decode_with_cache(
                params, config, c, lg, 8, prefill_length=12))
        with pytest.raises(ValueError, match="capacity"):
            decode_fn(cache, logits)
        # with headroom the same jit runs
        ok_fn = jax.jit(
            lambda c, lg: greedy_decode_with_cache(
                params, config, c, lg, 4, prefill_length=12))
        out = ok_fn(cache, logits)
        assert out.shape == (1, 4)
        # outside jit the cache's CONCRETE length stays authoritative: an
        # understated prefill_length must not bypass the real bound
        with pytest.raises(ValueError, match="capacity"):
            greedy_decode_with_cache(params, config, cache, logits, 8,
                                     prefill_length=4)

    def test_sampled_decode_from_cache_matches_one_shot(self):
        """sample_decode == prefill + sample_decode_with_cache under the
        same key (the sampled serving split)."""
        from kubeshare_tpu.models.decoding import (
            prefill, sample_decode, sample_decode_with_cache)
        from kubeshare_tpu.models.transformer import (
            TransformerConfig, transformer_init)

        config = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq_len=32, dtype=jnp.float32, attention="reference")
        params = transformer_init(jax.random.PRNGKey(0), config)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 64)
        rng = jax.random.PRNGKey(7)
        one_shot = sample_decode(params, config, prompt, rng, 6,
                                 temperature=0.8, top_k=10)
        cache, logits = prefill(params, config, prompt)
        split = sample_decode_with_cache(params, config, cache, logits,
                                         rng, 6, temperature=0.8, top_k=10)
        np.testing.assert_array_equal(np.asarray(one_shot),
                                      np.asarray(split))

    def test_chunked_prefill_ragged_and_chunk_validation(self):
        """Non-tiling prompts no longer raise: the ragged tail runs as
        one bucketed (power-of-two) chunk and must match the bulk
        prefill (tests/test_serving.py locks every remainder); a
        degenerate chunk still fails loudly."""
        from kubeshare_tpu.models.decoding import prefill, prefill_chunked
        from kubeshare_tpu.models.transformer import (
            TransformerConfig, transformer_init)

        config = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=1, d_ff=64,
            max_seq_len=32, dtype=jnp.float32, attention="reference")
        params = transformer_init(jax.random.PRNGKey(0), config)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 10), 0, 64)
        cache_b, logits_b = prefill(params, config, prompt)
        cache_c, logits_c = prefill_chunked(params, config, prompt, 4)
        np.testing.assert_allclose(
            np.asarray(logits_c), np.asarray(logits_b),
            rtol=2e-4, atol=2e-4)
        assert int(cache_c["length"]) == 10
        with pytest.raises(ValueError, match="chunk"):
            prefill_chunked(params, config, prompt, 0)

    def test_gqa_head_count_validated(self):
        from kubeshare_tpu.models.transformer import (
            TransformerConfig, transformer_init)

        config = TransformerConfig(
            vocab_size=8, d_model=24, n_heads=3, n_kv_heads=2, n_layers=1,
            d_ff=8, max_seq_len=8,
        )
        with pytest.raises(ValueError, match="multiple of n_kv_heads"):
            transformer_init(jax.random.PRNGKey(0), config)

    def test_greedy_decode_jits_and_is_deterministic(self):
        from kubeshare_tpu.models.decoding import greedy_decode

        config, params = self._setup()
        prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 4), 0, 64)
        decode = jax.jit(
            lambda p, t: greedy_decode(p, config, t, max_new_tokens=8)
        )
        out1 = decode(params, prompt)
        out2 = decode(params, prompt)
        assert out1.shape == (2, 8)
        np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
        assert (np.asarray(out1) >= 0).all() and (np.asarray(out1) < 64).all()

    def test_sliding_window_prefill_matches_dense(self):
        """A windowed model must decode with the same band the dense mask
        keeps (ADVICE r1: cached path used to attend over full history)."""
        from dataclasses import replace

        from kubeshare_tpu.models.decoding import (
            prefill_incremental as prefill)

        config, params = self._setup()
        config = replace(config, attention_window=4)
        prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 12), 0, 64)
        dense = transformer_apply(params, prompt, config)
        _, last_logits = prefill(params, config, prompt)
        np.testing.assert_allclose(
            np.asarray(dense[:, -1]), np.asarray(last_logits),
            rtol=2e-4, atol=2e-4,
        )
        # and it must differ from the un-windowed decode (mask is live)
        _, full_logits = prefill(params, replace(config, attention_window=None), prompt)
        assert not np.allclose(np.asarray(last_logits), np.asarray(full_logits))

    def test_overflow_guards(self):
        from kubeshare_tpu.models.decoding import greedy_decode, prefill

        config, params = self._setup()
        long_prompt = jnp.zeros((1, 40), jnp.int32)  # > max_seq_len 32
        with pytest.raises(ValueError):
            prefill(params, config, long_prompt)
        with pytest.raises(ValueError):
            greedy_decode(params, config, jnp.zeros((1, 30), jnp.int32), 10)


class TestShardedDecoding:
    """Multi-chip serving: decode with tensor-parallel-placed parameters.
    No decode-specific sharding code needed — the params' NamedShardings
    (transformer_sharding_rules) propagate through the KV-cache scan under
    jit, XLA inserting the tp collectives; these tests pin that the
    sharded path is bit-identical to single-device decode."""

    def _setup(self):
        from kubeshare_tpu.models.transformer import (
            TransformerConfig, transformer_init, transformer_sharding_rules)
        from kubeshare_tpu.parallel.mesh import shard_params

        config = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq_len=32, dtype=jnp.float32, attention="reference",
        )
        params = transformer_init(jax.random.PRNGKey(0), config)
        mesh = make_mesh(MeshSpec(dp=2, tp=2, sp=2))
        placed = shard_params(params, transformer_sharding_rules(), mesh)
        return config, params, placed

    def test_tp_sharded_greedy_matches_unsharded(self):
        from kubeshare_tpu.models.decoding import greedy_decode

        config, params, placed = self._setup()
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 4), 0, 64)
        base = greedy_decode(params, config, prompt, 8)
        sharded = jax.jit(
            lambda p, t: greedy_decode(p, config, t, 8))(placed, prompt)
        np.testing.assert_array_equal(np.asarray(base), np.asarray(sharded))

    def test_tp_sharded_sampling_matches_unsharded(self):
        from kubeshare_tpu.models.decoding import sample_decode

        config, params, placed = self._setup()
        prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 4), 0, 64)
        rng = jax.random.PRNGKey(3)
        base = sample_decode(params, config, prompt, rng, 6,
                             temperature=0.8, top_k=10)
        sharded = jax.jit(lambda p, t, r: sample_decode(
            p, config, t, r, 6, temperature=0.8, top_k=10))(
                placed, prompt, rng)
        np.testing.assert_array_equal(np.asarray(base), np.asarray(sharded))

    def test_gqa_tp_sharded_greedy_matches_unsharded(self):
        """The advertised combination — tp-sharded serving WITH a
        kv_heads-sized cache axis — decoded under placement."""
        from kubeshare_tpu.models.decoding import greedy_decode
        from kubeshare_tpu.models.transformer import (
            TransformerConfig, transformer_init, transformer_sharding_rules)
        from kubeshare_tpu.parallel.mesh import shard_params

        config = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_kv_heads=2, n_layers=2,
            d_ff=64, max_seq_len=32, dtype=jnp.float32,
            attention="reference",
        )
        params = transformer_init(jax.random.PRNGKey(0), config)
        mesh = make_mesh(MeshSpec(dp=2, tp=2, sp=2))
        placed = shard_params(params, transformer_sharding_rules(), mesh)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 4), 0, 64)
        base = greedy_decode(params, config, prompt, 8)
        sharded = jax.jit(
            lambda p, t: greedy_decode(p, config, t, 8))(placed, prompt)
        np.testing.assert_array_equal(np.asarray(base), np.asarray(sharded))

    def test_undivisible_tp_names_the_parameter(self):
        """A GQA config whose shrunken wk/wv head axis no longer divides
        tp must fail with the parameter path and axis named, not
        device_put's raw divisibility error."""
        from kubeshare_tpu.models.transformer import (
            TransformerConfig, transformer_init, transformer_sharding_rules)
        from kubeshare_tpu.parallel.mesh import shard_params

        config = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_kv_heads=1, n_layers=1,
            d_ff=64, max_seq_len=32, dtype=jnp.float32,
        )
        params = transformer_init(jax.random.PRNGKey(0), config)
        mesh = make_mesh(MeshSpec(dp=2, tp=2, sp=2))
        with pytest.raises(ValueError, match=r"wk.*axis 1.*tp=2"):
            shard_params(params, transformer_sharding_rules(), mesh)


class TestSpeculativeDecoding:
    """Draft-model speculation must emit EXACTLY greedy_decode's tokens —
    the acceptance rule preserves the target's argmax stream regardless
    of how good or bad the draft is."""

    def _target(self, **extra):
        from kubeshare_tpu.models.transformer import (
            TransformerConfig, transformer_init)

        config = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq_len=64, dtype=jnp.float32, attention="reference",
            **extra)
        return config, transformer_init(jax.random.PRNGKey(0), config)

    def test_self_draft_matches_greedy(self):
        """Draft == target: every proposal accepted, output identical."""
        from kubeshare_tpu.models.decoding import (
            greedy_decode, speculative_greedy_decode)

        config, params = self._target()
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 64)
        base = greedy_decode(params, config, prompt, 12)
        spec = speculative_greedy_decode(
            params, config, params, config, prompt, 12, draft_len=4)
        np.testing.assert_array_equal(np.asarray(base), np.asarray(spec))

    def test_bad_draft_still_matches_greedy(self):
        """A differently-initialized (frequently wrong) draft changes only
        the speed, never the tokens."""
        from kubeshare_tpu.models.transformer import (
            TransformerConfig, transformer_init)
        from kubeshare_tpu.models.decoding import (
            greedy_decode, speculative_greedy_decode)

        config, params = self._target(positional="rope", n_kv_heads=2)
        draft_config = TransformerConfig(
            vocab_size=64, d_model=16, n_heads=2, n_layers=1, d_ff=32,
            max_seq_len=64, dtype=jnp.float32, attention="reference")
        draft_params = transformer_init(jax.random.PRNGKey(9), draft_config)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 64)
        base = greedy_decode(params, config, prompt, 12)
        for draft_len in (2, 3, 5):
            spec = speculative_greedy_decode(
                params, config, draft_params, draft_config, prompt, 12,
                draft_len=draft_len)
            np.testing.assert_array_equal(
                np.asarray(base), np.asarray(spec),
                err_msg=f"draft_len={draft_len}")

    def test_jits(self):
        from kubeshare_tpu.models.decoding import speculative_greedy_decode

        config, params = self._target()
        prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 4), 0, 64)
        fn = jax.jit(lambda p, t: speculative_greedy_decode(
            p, config, p, config, t, 8))
        out1 = fn(params, prompt)
        out2 = fn(params, prompt)
        np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
        assert out1.shape == (1, 8)

    def test_validation(self):
        from kubeshare_tpu.models.transformer import (
            TransformerConfig, transformer_init)
        from kubeshare_tpu.models.decoding import speculative_greedy_decode

        config, params = self._target()
        prompt = jnp.zeros((1, 4), jnp.int32)
        with pytest.raises(ValueError, match="draft_len"):
            speculative_greedy_decode(params, config, params, config,
                                      prompt, 8, draft_len=1)
        other_vocab = TransformerConfig(
            vocab_size=32, d_model=16, n_heads=2, n_layers=1, d_ff=32,
            max_seq_len=64)
        other_params = transformer_init(jax.random.PRNGKey(0), other_vocab)
        with pytest.raises(ValueError, match="vocabular"):
            speculative_greedy_decode(params, config, other_params,
                                      other_vocab, prompt, 8)
        with pytest.raises(ValueError, match="headroom"):
            speculative_greedy_decode(params, config, params, config,
                                      prompt, 60)


class TestSpeculativeSampling:
    """Stochastic speculative decoding (VERDICT r4 #5): the rejection-
    sampling acceptance rule must leave the emitted stream distributed
    EXACTLY as sample_decode's — locked by an empirical distribution-
    equivalence test — while a good draft cuts target passes."""

    def _models(self, vocab=16):
        from kubeshare_tpu.models.transformer import (
            TransformerConfig, transformer_init)

        config = TransformerConfig(
            vocab_size=vocab, d_model=16, n_heads=2, n_layers=1, d_ff=32,
            max_seq_len=32, dtype=jnp.float32, attention="reference")
        params = transformer_init(jax.random.PRNGKey(0), config)
        draft_config = TransformerConfig(
            vocab_size=vocab, d_model=8, n_heads=1, n_layers=1, d_ff=16,
            max_seq_len=32, dtype=jnp.float32, attention="reference")
        draft_params = transformer_init(jax.random.PRNGKey(7), draft_config)
        return config, params, draft_config, draft_params

    def test_distribution_matches_sample_decode(self):
        """Empirical per-position token distributions of the speculative
        sampler and the plain sampler must agree within sampling noise
        (N=1500 lanes; TV tolerance sized ~3x the expected noise — a
        wrong acceptance ratio or residual shifts TV far more)."""
        from kubeshare_tpu.models.decoding import (
            sample_decode, speculative_sample_decode)

        config, params, dconfig, dparams = self._models()
        prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 4), 0, 16)
        n, steps = 1500, 3
        keys = jax.random.split(jax.random.PRNGKey(42), n)

        plain = jax.jit(jax.vmap(
            lambda k: sample_decode(params, config, prompt, k, steps,
                                    temperature=0.9, top_k=12)))(keys)
        spec = jax.jit(jax.vmap(
            lambda k: speculative_sample_decode(
                params, config, dparams, dconfig, prompt, k, steps,
                draft_len=3, temperature=0.9, top_k=12)))(keys)
        plain = np.asarray(plain)[:, 0, :]  # [n, steps]
        spec = np.asarray(spec)[:, 0, :]
        for pos in range(steps):
            h_plain = np.bincount(plain[:, pos], minlength=16) / n
            h_spec = np.bincount(spec[:, pos], minlength=16) / n
            tv = 0.5 * np.abs(h_plain - h_spec).sum()
            assert tv < 0.12, (
                f"position {pos}: TV distance {tv:.3f} between plain and "
                f"speculative sampling (plain {h_plain}, spec {h_spec})")

    def test_self_draft_accepts_every_proposal(self):
        """Draft == target makes the acceptance ratio exactly 1: every
        round emits draft_len tokens, so the target-pass count hits the
        theoretical floor ceil((max_new - 1) / draft_len)."""
        from kubeshare_tpu.models.decoding import speculative_sample_decode

        config, params, _, _ = self._models()
        prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 4), 0, 16)
        out, stats = speculative_sample_decode(
            params, config, params, config, prompt,
            jax.random.PRNGKey(3), 12, draft_len=3, return_stats=True)
        assert out.shape == (2, 12)
        assert int(stats["rounds"]) == 4  # ceil(11 / 3)
        # the greedy variant exposes the same stat (benchmarks report
        # measured tokens-per-target-pass rather than assuming accept=1)
        from kubeshare_tpu.models.decoding import speculative_greedy_decode

        gout, gstats = speculative_greedy_decode(
            params, config, params, config, prompt, 12, draft_len=3,
            return_stats=True)
        assert gout.shape == (2, 12)
        assert int(gstats["rounds"]) == 4

    def test_temperature_zero_delegates_to_greedy(self):
        from kubeshare_tpu.models.decoding import (
            greedy_decode, speculative_sample_decode)

        config, params, dconfig, dparams = self._models()
        prompt = jax.random.randint(jax.random.PRNGKey(4), (1, 4), 0, 16)
        spec = speculative_sample_decode(
            params, config, dparams, dconfig, prompt,
            jax.random.PRNGKey(5), 8, temperature=0.0)
        base = greedy_decode(params, config, prompt, 8)
        np.testing.assert_array_equal(np.asarray(base), np.asarray(spec))

    def test_deterministic_under_same_key(self):
        from kubeshare_tpu.models.decoding import speculative_sample_decode

        config, params, dconfig, dparams = self._models()
        prompt = jax.random.randint(jax.random.PRNGKey(6), (2, 4), 0, 16)
        fn = jax.jit(lambda k: speculative_sample_decode(
            params, config, dparams, dconfig, prompt, k, 10, draft_len=4,
            top_p=0.95))
        k = jax.random.PRNGKey(8)
        np.testing.assert_array_equal(np.asarray(fn(k)), np.asarray(fn(k)))

    def test_validation(self):
        from kubeshare_tpu.models.decoding import speculative_sample_decode

        config, params, dconfig, dparams = self._models()
        prompt = jnp.zeros((1, 4), jnp.int32)
        rng = jax.random.PRNGKey(0)
        with pytest.raises(ValueError, match="max_new_tokens"):
            speculative_sample_decode(params, config, dparams, dconfig,
                                      prompt, rng, 0)
        with pytest.raises(ValueError, match="draft_len"):
            speculative_sample_decode(params, config, dparams, dconfig,
                                      prompt, rng, 8, draft_len=1)
        with pytest.raises(ValueError, match="temperature"):
            speculative_sample_decode(params, config, dparams, dconfig,
                                      prompt, rng, 8, temperature=-1.0)


class TestSampledDecoding:
    _setup = TestDecoding._setup

    def test_temperature_zero_is_greedy(self):
        from kubeshare_tpu.models.decoding import greedy_decode, sample_decode

        config, params = self._setup()
        prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 4), 0, 64)
        greedy = greedy_decode(params, config, prompt, max_new_tokens=8)
        sampled = sample_decode(params, config, prompt,
                                jax.random.PRNGKey(7), 8, temperature=0.0)
        np.testing.assert_array_equal(np.asarray(greedy), np.asarray(sampled))

    def test_top_k_one_is_greedy(self):
        from kubeshare_tpu.models.decoding import greedy_decode, sample_decode

        config, params = self._setup()
        prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 4), 0, 64)
        greedy = greedy_decode(params, config, prompt, max_new_tokens=6)
        sampled = sample_decode(params, config, prompt,
                                jax.random.PRNGKey(9), 6, temperature=1.0,
                                top_k=1)
        np.testing.assert_array_equal(np.asarray(greedy), np.asarray(sampled))

    def test_jit_deterministic_under_same_key(self):
        from kubeshare_tpu.models.decoding import sample_decode

        config, params = self._setup()
        prompt = jax.random.randint(jax.random.PRNGKey(4), (2, 4), 0, 64)
        decode = jax.jit(lambda p, t, r: sample_decode(
            p, config, t, r, 8, temperature=0.8, top_k=10, top_p=0.9))
        out1 = decode(params, prompt, jax.random.PRNGKey(5))
        out2 = decode(params, prompt, jax.random.PRNGKey(5))
        np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
        assert out1.shape == (2, 8)
        assert (np.asarray(out1) >= 0).all() and (np.asarray(out1) < 64).all()
        # a different key must be able to produce a different sequence
        out3 = decode(params, prompt, jax.random.PRNGKey(6))
        assert not np.array_equal(np.asarray(out1), np.asarray(out3))

    def test_filter_logits_top_k(self):
        from kubeshare_tpu.models.decoding import _filter_logits

        logits = jnp.asarray([[1.0, 5.0, 3.0, 2.0]])
        out = np.asarray(_filter_logits(logits, top_k=2, top_p=None))
        assert np.isfinite(out[0, 1]) and np.isfinite(out[0, 2])
        assert np.isneginf(out[0, 0]) and np.isneginf(out[0, 3])
        # top_k >= vocab keeps everything (explicit clamp, ADVICE r2)
        out = np.asarray(_filter_logits(logits, top_k=100, top_p=None))
        assert np.isfinite(out).all()

    def test_filter_logits_top_p(self):
        from kubeshare_tpu.models.decoding import _filter_logits

        # softmax of [2, 1, 0, -10] ~= [0.70, 0.26, 0.095, ~0]: top_p=0.5
        # keeps only the first (its mass alone reaches 0.5)
        logits = jnp.asarray([[2.0, 1.0, 0.0, -10.0]])
        out = np.asarray(_filter_logits(logits, top_k=None, top_p=0.5))
        assert np.isfinite(out[0, 0])
        assert np.isneginf(out[0, 1:]).all()
        # top_p=1.0 keeps everything
        out = np.asarray(_filter_logits(logits, top_k=None, top_p=1.0))
        assert np.isfinite(out).all()

    def test_argument_validation(self):
        from kubeshare_tpu.models.decoding import _filter_logits, sample_decode

        config, params = self._setup()
        with pytest.raises(ValueError):
            sample_decode(params, config, jnp.zeros((1, 4), jnp.int32),
                          jax.random.PRNGKey(0), 8, temperature=-1.0)
        with pytest.raises(ValueError):
            sample_decode(params, config, jnp.zeros((1, 30), jnp.int32),
                          jax.random.PRNGKey(0), 10)
        with pytest.raises(ValueError):
            _filter_logits(jnp.zeros((1, 4)), top_k=0, top_p=None)
        with pytest.raises(ValueError):
            _filter_logits(jnp.zeros((1, 4)), top_k=None, top_p=1.5)


class TestFlashKTiling:
    def test_multiple_k_blocks(self):
        from kubeshare_tpu.ops.attention import _flash_forward

        q, k, v = (rand(i, 1, 2, 64, 8) for i in range(3))
        for causal in (True, False):
            ref = attention_reference(q, k, v, causal)
            out, lse = _flash_forward(q, k, v, causal, block_q=16,
                                      interpret=True, block_k=16)
            assert lse.shape == q.shape[:3] + (1,)
            np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                       rtol=2e-4, atol=2e-4)

    def test_k_tiling_gradients(self):
        q, k, v = (rand(i, 1, 1, 32, 8) for i in range(3))

        def loss(q, k, v):
            return flash_attention(q, k, v, block_q=8, use_pallas=True,
                                   interpret=True).sum()

        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(
            lambda q, k, v: attention_reference(q, k, v).sum(),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)


class TestFlashBackwardKernels:
    def test_grads_multi_block_causal_and_not(self):
        q, k, v = (rand(i, 2, 2, 64, 8) for i in range(3))
        for causal in (True, False):
            def loss(q, k, v):
                return (flash_attention(q, k, v, causal=causal, block_q=16,
                                        use_pallas=True, interpret=True) ** 2).sum()

            def loss_ref(q, k, v):
                return (attention_reference(q, k, v, causal) ** 2).sum()

            g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
            g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
            for a, b in zip(g, g_ref):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=2e-4, atol=2e-4)

    def test_value_and_grad_through_training_loss(self):
        # end-to-end: attention inside a toy loss with value_and_grad
        q, k, v = (rand(i, 1, 2, 32, 8) for i in range(3))
        targets = rand(9, 1, 2, 32, 8)

        def loss(q, k, v):
            out = flash_attention(q, k, v, block_q=8, use_pallas=True,
                                  interpret=True)
            return jnp.mean((out - targets) ** 2)

        (val, grads) = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
        assert np.isfinite(float(val))
        for g in grads:
            assert np.isfinite(np.asarray(g)).all()


class TestFlashBackwardFallback:
    def test_non_tiling_seq_uses_reference_grads(self):
        # s=320 tiles the forward blocks (bq=64, bk=min(1024,320)=320) but
        # not the backward defaults (256/512): must fall back, not truncate
        q, k, v = (rand(i, 1, 2, 320, 8) for i in range(3))

        def loss(q, k, v):
            return flash_attention(q, k, v, block_q=64, use_pallas=True,
                                   interpret=True).sum()

        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(
            lambda q, k, v: attention_reference(q, k, v).sum(),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, g_ref):
            assert np.isfinite(np.asarray(a)).all()
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)


class TestSlidingWindowAttention:
    def test_window_matches_reference(self):
        q, k, v = (rand(i, 1, 2, 64, 8) for i in range(3))
        for window in (8, 16, 64):
            ref = attention_reference(q, k, v, causal=True, window=window)
            out = flash_attention(q, k, v, block_q=16, use_pallas=True,
                                  interpret=True, window=window)
            np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                       rtol=2e-4, atol=2e-4)

    def test_window_gradients(self):
        q, k, v = (rand(i, 1, 1, 32, 8) for i in range(3))

        def loss(q, k, v):
            return flash_attention(q, k, v, block_q=8, use_pallas=True,
                                   interpret=True, window=8).sum()

        def loss_ref(q, k, v):
            return attention_reference(q, k, v, True, window=8).sum()

        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_window_equals_full_causal(self):
        # window >= seq is exactly causal attention
        q, k, v = (rand(i, 1, 1, 32, 8) for i in range(3))
        full = attention_reference(q, k, v, causal=True)
        windowed = flash_attention(q, k, v, block_q=8, use_pallas=True,
                                   interpret=True, window=32)
        np.testing.assert_allclose(np.asarray(full), np.asarray(windowed),
                                   rtol=2e-4, atol=2e-4)

    def test_window_with_multiple_k_blocks(self):
        # force several K blocks so the band-skip clause actually runs
        from kubeshare_tpu.ops.attention import _flash_forward

        q, k, v = (rand(i, 1, 2, 64, 8) for i in range(3))
        for window in (8, 24, 40):
            ref = attention_reference(q, k, v, causal=True, window=window)
            out, _ = _flash_forward(q, k, v, True, 16, True, block_k=16,
                                    window=window)
            np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                       rtol=2e-4, atol=2e-4)

    def test_window_backward_multiple_blocks(self):
        # s=1024 -> bwd blocks 256/512: several blocks in both sweeps
        q, k, v = (rand(i, 1, 1, 1024, 8) for i in range(3))

        def loss(q, k, v):
            return flash_attention(q, k, v, use_pallas=True, interpret=True,
                                   window=300).sum()

        def loss_ref(q, k, v):
            return attention_reference(q, k, v, True, window=300).sum()

        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-3)

    def test_invalid_window_rejected(self):
        q = rand(0, 1, 1, 16, 8)
        with pytest.raises(ValueError):
            flash_attention(q, q, q, window=0)
        with pytest.raises(ValueError):
            attention_reference(q, q, q, window=-5)


class TestGQA:
    def test_gqa_matches_repeated_reference(self):
        q = rand(0, 1, 8, 64, 16)
        k = rand(1, 1, 2, 64, 16)  # 2 kv heads, group of 4
        v = rand(2, 1, 2, 64, 16)
        k_full = jnp.repeat(k, 4, axis=1)
        v_full = jnp.repeat(v, 4, axis=1)
        ref = attention_reference(q, k_full, v_full, causal=True)
        out = flash_attention(q, k, v, block_q=16, use_pallas=True,
                              interpret=True)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=2e-4, atol=2e-4)

    def test_gqa_gradients(self):
        q = rand(0, 1, 4, 32, 8)
        k = rand(1, 1, 2, 32, 8)
        v = rand(2, 1, 2, 32, 8)

        def loss(q, k, v):
            return flash_attention(q, k, v, block_q=8, use_pallas=True,
                                   interpret=True).sum()

        def loss_ref(q, k, v):
            return attention_reference(
                q, jnp.repeat(k, 2, axis=1), jnp.repeat(v, 2, axis=1)
            ).sum()

        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        # reference grads for grouped kv: sum over the repeat
        gq_ref, gk_full, gv_full = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(np.asarray(g[0]), np.asarray(gq_ref),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(g[1]), np.asarray(gk_full),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(g[2]), np.asarray(gv_full),
                                   rtol=1e-4, atol=1e-4)

    def test_bad_head_ratio_rejected(self):
        q = rand(0, 1, 6, 16, 8)
        k = rand(1, 1, 4, 16, 8)
        with pytest.raises(ValueError):
            flash_attention(q, k, k, block_q=8, use_pallas=True, interpret=True)


class TestRope:
    def test_rope_shapes_and_rotation_identity(self):
        from kubeshare_tpu.ops.rope import apply_rope, rope_positions

        x = rand(0, 2, 4, 16, 8)
        out = apply_rope(x, rope_positions(16))
        assert out.shape == x.shape
        # position 0 is the identity rotation
        np.testing.assert_allclose(np.asarray(out[:, :, 0]),
                                   np.asarray(x[:, :, 0]), rtol=1e-5)
        # rotation preserves pair norms
        def pair_norms(a):
            a1, a2 = np.split(np.asarray(a, np.float64), 2, axis=-1)
            return a1**2 + a2**2
        np.testing.assert_allclose(pair_norms(out), pair_norms(x), rtol=1e-4)

    def test_rope_relative_shift_invariance(self):
        from kubeshare_tpu.ops.rope import apply_rope, rope_positions

        # attention scores depend only on relative positions
        q = rand(0, 1, 1, 8, 8)
        k = rand(1, 1, 1, 8, 8)
        def scores(offset):
            pos = rope_positions(8, offset)
            qr, kr = apply_rope(q, pos), apply_rope(k, pos)
            return np.asarray(jnp.einsum("bhqd,bhkd->bhqk", qr, kr))
        np.testing.assert_allclose(scores(0), scores(17), rtol=1e-4, atol=1e-5)

    def test_rope_transformer_and_decode_consistent(self):
        from kubeshare_tpu.models.decoding import (
            prefill_incremental as prefill)

        config = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq_len=32, dtype=jnp.float32, attention="reference",
            positional="rope",
        )
        params = transformer_init(jax.random.PRNGKey(0), config)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 10), 0, 64)
        dense = transformer_apply(params, prompt, config)
        _, last_logits = prefill(params, config, prompt)
        np.testing.assert_allclose(np.asarray(dense[:, -1]),
                                   np.asarray(last_logits),
                                   rtol=2e-4, atol=2e-4)

    def test_rope_ring_matches_dense(self):
        from kubeshare_tpu.models.transformer import transformer_apply_ring

        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        config = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq_len=64, dtype=jnp.float32, attention="reference",
            positional="rope",
        )
        params = transformer_init(jax.random.PRNGKey(0), config)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64)
        dense = transformer_apply(params, tokens, config)
        ring = transformer_apply_ring(params, tokens, config, mesh)
        np.testing.assert_allclose(np.asarray(dense), np.asarray(ring),
                                   rtol=2e-4, atol=2e-4)

    def test_rope_config_validation_and_no_pos_table(self):
        config = TransformerConfig(
            vocab_size=16, d_model=16, n_heads=2, n_layers=1, d_ff=16,
            max_seq_len=16, dtype=jnp.float32, attention="reference",
            positional="rope",
        )
        params = transformer_init(jax.random.PRNGKey(0), config)
        assert "pos_embed" not in params  # no dead table under rope
        bad = TransformerConfig(
            vocab_size=16, d_model=16, n_heads=2, n_layers=1, d_ff=16,
            max_seq_len=16, dtype=jnp.float32, positional="Rotary",
        )
        with pytest.raises(ValueError):
            transformer_init(jax.random.PRNGKey(0), bad)


class TestRemat:
    def test_remat_grads_match(self):
        base = dict(vocab_size=32, d_model=16, n_heads=2, n_layers=2,
                    d_ff=32, max_seq_len=16, dtype=jnp.float32,
                    attention="reference")
        plain = TransformerConfig(**base)
        remat = TransformerConfig(**base, remat=True)
        params = transformer_init(jax.random.PRNGKey(0), plain)
        tokens = jnp.ones((2, 8), jnp.int32)

        def loss(config):
            return lambda p: (transformer_apply(p, tokens, config) ** 2).mean()

        g_plain = jax.grad(loss(plain))(params)
        g_remat = jax.grad(loss(remat))(params)
        for a, b in zip(jax.tree.leaves(g_plain), jax.tree.leaves(g_remat)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)


class TestMoEFlagship:
    """MoE layers inside the flagship Transformer (config.moe_every)."""

    def _config(self, **kw):
        kw.setdefault("moe_every", 2)
        kw.setdefault("moe_num_experts", 4)
        kw.setdefault("moe_capacity_factor", 8.0)  # ample: no token drops
        kw.setdefault("attention", "reference")
        return TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=4, d_ff=64,
            max_seq_len=32, dtype=jnp.float32, **kw)

    def test_init_places_moe_layers(self):
        config = self._config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        kinds = ["moe" if "moe" in l else "mlp" for l in params["layers"]]
        assert kinds == ["mlp", "moe", "mlp", "moe"]
        assert params["layers"][1]["moe"]["w_in"].shape == (4, 32, 64)

    def test_forward_and_aux(self):
        config = self._config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
        logits = transformer_apply(params, tokens, config)
        assert logits.shape == (2, 16, 64)
        assert np.isfinite(np.asarray(logits)).all()
        logits2, aux = transformer_apply_with_aux(params, tokens, config)
        np.testing.assert_array_equal(np.asarray(logits), np.asarray(logits2))
        assert float(aux) > 0.0  # two MoE layers contribute load-balance loss

    def test_router_gets_gradients_through_aux(self):
        config = self._config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, 64)

        def loss(p):
            logits, aux = transformer_apply_with_aux(p, tokens, config)
            targets = jnp.zeros(tokens.shape, jnp.int32)
            return cross_entropy_loss(logits, targets) + 0.01 * aux

        grads = jax.grad(loss)(params)
        g_router = np.asarray(grads["layers"][1]["moe"]["router"])
        assert np.isfinite(g_router).all()
        assert np.abs(g_router).sum() > 0

    def test_decode_matches_full_forward(self):
        from kubeshare_tpu.models.decoding import (
            prefill_incremental as prefill)

        config = self._config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 10), 0, 64)
        dense = transformer_apply(params, prompt, config)
        _, last_logits = prefill(params, config, prompt)
        np.testing.assert_allclose(
            np.asarray(dense[:, -1]), np.asarray(last_logits),
            rtol=2e-4, atol=2e-4)

    def test_sampled_decode_runs(self):
        from kubeshare_tpu.models.decoding import sample_decode

        config = self._config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        prompt = jnp.zeros((1, 4), jnp.int32)
        toks = sample_decode(params, config, prompt, jax.random.PRNGKey(5),
                             6, temperature=0.8, top_k=8)
        assert toks.shape == (1, 6)

    def test_sharding_rules_place_experts_on_tp(self):
        from kubeshare_tpu.models.transformer import transformer_sharding_rules
        from kubeshare_tpu.parallel.mesh import shard_params

        config = self._config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        mesh = make_mesh(MeshSpec(dp=2, tp=2, sp=2))
        placed = shard_params(params, transformer_sharding_rules(), mesh)
        moe = placed["layers"][1]["moe"]
        assert moe["w_in"].sharding.spec == P("tp", None, None)
        assert moe["w_out"].sharding.spec == P("tp", None, None)
        assert moe["router"].sharding.spec == P()
        # tp-sharded forward still matches unsharded
        tokens = jax.random.randint(jax.random.PRNGKey(6), (2, 8), 0, 64)
        base = transformer_apply(params, tokens, config)
        sharded = jax.jit(
            lambda p, t: transformer_apply(p, t, config))(placed, tokens)
        np.testing.assert_allclose(np.asarray(base), np.asarray(sharded),
                                   rtol=2e-4, atol=2e-4)

    def test_sp_entries_accept_token_choice_moe(self):
        """Round 4: the standalone sp entries route MoE per shard
        (TestMoESequenceParallel locks dense equivalence); only
        expert-choice routing — whole-batch by construction — is
        rejected there."""
        from dataclasses import replace

        from kubeshare_tpu.models.transformer import transformer_apply_ring

        config = self._config(attention="ring")
        params = transformer_init(jax.random.PRNGKey(0), self._config())
        mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
        out = transformer_apply_ring(params, jnp.zeros((2, 8), jnp.int32),
                                     config, mesh)
        assert np.isfinite(np.asarray(out)).all()
        ec = replace(config, moe_routing="experts_choose")
        with pytest.raises(ValueError, match="whole-batch"):
            transformer_apply_ring(params, jnp.zeros((2, 8), jnp.int32),
                                   ec, mesh)

    @pytest.mark.parametrize("attention", ["reference", "ring"])
    def test_pipelined_paths_reject_moe(self, attention):
        """Both pipelined branches (dense AND sp-in-stage) must refuse MoE
        configs — the stage body would otherwise silently run MoE layers
        with default routing hyperparameters and drop the aux loss."""
        from jax.sharding import Mesh
        from kubeshare_tpu.models.transformer import (
            transformer_apply_pipelined, transformer_train_1f1b)

        config = self._config(attention=attention, moe_every=1,
                              positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), self._config())
        shape = (2, 2) if attention == "ring" else (2,)
        axes = ("pp", "sp") if attention == "ring" else ("pp",)
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(*shape)
                    if attention == "ring"
                    else np.array(jax.devices()[:2]).reshape(2), axes)
        tokens = jnp.zeros((2, 8), jnp.int32)
        with pytest.raises(ValueError, match="MoE"):
            transformer_apply_pipelined(params, tokens, config, mesh)
        with pytest.raises(ValueError, match="MoE"):
            transformer_train_1f1b(params, tokens, tokens, config, mesh)

    def test_top2_forward_grads_and_decode_parity(self):
        """The flagship wired for GShard-style top-2 (config.moe_top_k=2):
        forward + grads finite, and incremental decode matches the dense
        forward — the dispatch/combine paths must agree for k>1 too."""
        from kubeshare_tpu.models.decoding import prefill

        config = self._config(moe_top_k=2)
        params = transformer_init(jax.random.PRNGKey(0), config)
        tokens = jax.random.randint(jax.random.PRNGKey(8), (2, 12), 0, 64)
        logits, aux = transformer_apply_with_aux(params, tokens, config)
        assert np.isfinite(np.asarray(logits)).all()
        assert float(aux) > 0.0

        def loss(p):
            lg, ax = transformer_apply_with_aux(p, tokens, config)
            return cross_entropy_loss(lg, jnp.zeros_like(tokens)) + 0.01 * ax

        grads = jax.grad(loss)(params)
        for li in (1, 3):
            g = np.asarray(grads["layers"][li]["moe"]["w_in"])
            assert np.isfinite(g).all() and np.abs(g).sum() > 0

        dense = transformer_apply(params, tokens, config)
        _, last_logits = prefill(params, config, tokens)
        np.testing.assert_allclose(
            np.asarray(dense[:, -1]), np.asarray(last_logits),
            rtol=2e-4, atol=2e-4)

    def test_experts_choose_flagship_trains_but_refuses_decode(self):
        """moe_routing='experts_choose': training works (grads finite,
        zero aux), incremental decode raises — expert choices depend on
        the whole sequence and cannot be replayed token-by-token."""
        from kubeshare_tpu.models.decoding import prefill

        config = self._config(moe_routing="experts_choose",
                              moe_capacity_factor=2.0)
        params = transformer_init(jax.random.PRNGKey(0), config)
        tokens = jax.random.randint(jax.random.PRNGKey(9), (2, 12), 0, 64)
        logits, aux = transformer_apply_with_aux(params, tokens, config)
        assert np.isfinite(np.asarray(logits)).all()
        assert float(aux) == 0.0

        grads = jax.grad(lambda p: cross_entropy_loss(
            transformer_apply(p, tokens, config), tokens))(params)
        g = np.asarray(grads["layers"][1]["moe"]["w_in"])
        assert np.isfinite(g).all() and np.abs(g).sum() > 0

        with pytest.raises(ValueError, match="expert-choice"):
            prefill(params, config, tokens)

    def test_decode_batch_independent_at_default_capacity(self):
        """Batched incremental decode must equal per-row decode even at the
        default capacity_factor (1.25): the decode path pins capacity to the
        per-step token count, so expert collisions between batch rows can
        never drop a row's token (ADVICE r2, decoding.py)."""
        from kubeshare_tpu.models.decoding import prefill

        config = self._config(moe_capacity_factor=1.25)
        params = transformer_init(jax.random.PRNGKey(0), config)
        # batch 4 over 4 experts: some step almost surely routes two rows
        # to the same expert, which the old factor-derived capacity dropped
        prompt = jax.random.randint(jax.random.PRNGKey(7), (4, 8), 0, 64)
        _, batched = prefill(params, config, prompt)
        for row in range(prompt.shape[0]):
            _, single = prefill(params, config, prompt[row:row + 1])
            np.testing.assert_allclose(
                np.asarray(batched[row:row + 1]), np.asarray(single),
                rtol=2e-4, atol=2e-4)


class TestMoECapacity:
    def test_capacity_rounds_up(self):
        """capacity = ceil(cf*n/e), not floor (ADVICE r2, moe.py): route all
        5 tokens to expert 0 with cf=1.0, e=4 -> capacity must be 2, so
        exactly 2 token rows survive (floor kept only 1)."""
        from kubeshare_tpu.ops.moe import MoEConfig, moe_apply, moe_init

        config = MoEConfig(d_model=8, d_ff=16, num_experts=4,
                           capacity_factor=1.0)
        params = dict(moe_init(jax.random.PRNGKey(0), config))
        router = np.zeros((8, 4), np.float32)
        router[:, 0] = 100.0  # positive-sum tokens all argmax to expert 0
        params["router"] = jnp.asarray(router)
        x = 0.1 + jnp.abs(
            jax.random.normal(jax.random.PRNGKey(1), (1, 5, 8), jnp.float32))
        out, _ = moe_apply(params, x, config)
        kept_rows = np.abs(np.asarray(out[0])).sum(axis=-1) > 0
        assert kept_rows.sum() == 2

    def test_capacity_override_keeps_all_tokens(self):
        from kubeshare_tpu.ops.moe import MoEConfig, moe_apply, moe_init

        config = MoEConfig(d_model=8, d_ff=16, num_experts=4,
                           capacity_factor=1.0)
        params = moe_init(jax.random.PRNGKey(0), config)
        x = jax.random.normal(jax.random.PRNGKey(2), (2, 6, 8), jnp.float32)
        ample = moe_apply(params, x, config, capacity=12)[0]
        huge_cf = moe_apply(
            params, x,
            MoEConfig(d_model=8, d_ff=16, num_experts=4,
                      capacity_factor=100.0))[0]
        np.testing.assert_allclose(np.asarray(ample), np.asarray(huge_cf),
                                   rtol=1e-6, atol=1e-6)
