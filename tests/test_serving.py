"""Serving subsystem tests: paged KV cache, continuous batching, ragged
prefill buckets.

The contract under test is the strongest one a serving stack can make:
the paged pool + continuous-batching engine must emit EXACTLY the token
stream the dense-cache reference paths emit — per request, regardless of
what else is co-batched in the pool, which slot the request landed in,
or whose blocks it recycled.  Plus the allocator's loud-failure
discipline and the zero-recompile property the TPU serving story depends
on.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeshare_tpu.models.transformer import TransformerConfig, transformer_init

pytestmark = pytest.mark.serving


def _small_config(**extra):
    return TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=64, dtype=jnp.float32, attention="reference", **extra)


def _engine(params, config, **overrides):
    from kubeshare_tpu.serving import EngineConfig, ServingEngine

    kwargs = dict(num_slots=3, block_size=4, num_blocks=41,
                  max_request_len=48, prefill_chunk=8)
    kwargs.update(overrides)
    return ServingEngine(params, config, EngineConfig(**kwargs))


class TestBlockAllocator:
    def test_exhaustion_is_loud_and_all_or_nothing(self):
        from kubeshare_tpu.serving import BlockAllocator, BlockExhausted

        alloc = BlockAllocator(num_blocks=5, block_size=4)  # 4 allocatable
        got = alloc.reserve(3, "a")
        assert len(got) == 3 and 0 not in got
        with pytest.raises(BlockExhausted, match="needs 2 blocks"):
            alloc.reserve(2, "b")
        # the failed reservation granted NOTHING
        assert alloc.free_blocks == 1
        assert alloc.blocks_in_use == 3

    def test_double_free_raises(self):
        from kubeshare_tpu.serving import BlockAllocator

        alloc = BlockAllocator(num_blocks=5, block_size=4)
        blocks = alloc.reserve(2, "a")
        alloc.reclaim(blocks)
        with pytest.raises(ValueError, match="double free"):
            alloc.reclaim(blocks)
        with pytest.raises(ValueError, match="not allocated"):
            alloc.reclaim([0])  # the scratch block is never allocated

    def test_reclaimed_blocks_are_reused_first(self):
        from kubeshare_tpu.serving import BlockAllocator

        alloc = BlockAllocator(num_blocks=9, block_size=4)
        first = alloc.reserve(3, "a")
        alloc.reclaim(first)
        again = alloc.reserve(3, "b")
        # LIFO free list: the retired request's blocks come back first
        assert set(again) == set(first)

    def test_blocks_for_tokens(self):
        from kubeshare_tpu.serving import BlockAllocator

        alloc = BlockAllocator(num_blocks=9, block_size=4)
        assert [alloc.blocks_for_tokens(n) for n in (1, 4, 5, 8, 9)] == [
            1, 1, 2, 2, 3]


class TestPagedEquivalence:
    """Greedy and sampled streams from the paged pool must match the
    dense cache exactly — the bit-exactness the ISSUE's read path
    promises, locked at the emitted-token level."""

    def test_greedy_matches_dense_across_configs(self):
        from kubeshare_tpu.models.decoding import greedy_decode
        from kubeshare_tpu.serving import Request

        cases = {
            "mha": dict(),
            "gqa_rope": dict(n_kv_heads=2, positional="rope"),
            "windowed": dict(attention_window=6),
            "moe": dict(moe_every=2, moe_num_experts=4, moe_top_k=2),
        }
        for name, extra in cases.items():
            config = _small_config(**extra)
            params = transformer_init(jax.random.PRNGKey(0), config)
            prompt = np.asarray(jax.random.randint(
                jax.random.PRNGKey(1), (13,), 0, 64), np.int32)
            dense = np.asarray(greedy_decode(
                params, config, jnp.asarray(prompt)[None], 8))[0]
            engine = _engine(params, config)
            engine.submit(Request("r0", prompt, 8))
            out = engine.run()["r0"]
            assert out.tokens == list(dense), name

    def test_sampled_matches_dense(self):
        """Same rng => the engine reproduces sample_decode_with_cache's
        stream exactly (temperature + top-k + top-p filtered)."""
        from kubeshare_tpu.models.decoding import sample_decode
        from kubeshare_tpu.serving import Request

        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        prompt = np.asarray(jax.random.randint(
            jax.random.PRNGKey(1), (10,), 0, 64), np.int32)
        rng = jax.random.PRNGKey(7)
        dense = np.asarray(sample_decode(
            params, config, jnp.asarray(prompt)[None], rng, 6,
            temperature=0.8, top_k=10, top_p=0.95))[0]
        engine = _engine(params, config, top_k=10, top_p=0.95)
        engine.submit(Request("r0", prompt, 6, temperature=0.8, rng=rng))
        out = engine.run()["r0"]
        assert out.tokens == list(dense)

    def test_paged_pool_rows_match_dense_cache(self):
        """Below the token level: the slot's gathered K/V rows equal the
        dense cache's rows after the same prefill."""
        from kubeshare_tpu.models.decoding import prefill
        from kubeshare_tpu.serving import Request, paged_gather_kv

        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        prompt = np.asarray(jax.random.randint(
            jax.random.PRNGKey(2), (11,), 0, 64), np.int32)
        dense_cache, _ = prefill(params, config, jnp.asarray(prompt)[None])
        engine = _engine(params, config)
        engine.submit(Request("r0", prompt, 1))
        engine.run()
        # request retired; its prompt blocks are now in the prefix
        # cache — look them up by CONTENT and rebuild the virtual view
        matched, blocks = engine.prefix_index.match(prompt)
        assert matched == 11 and len(blocks) == 3  # 2 full + partial tail
        table = np.zeros(engine._table_width, np.int32)
        table[: len(blocks)] = blocks
        k_view, _ = paged_gather_kv(engine.pool.k, engine.pool.v,
                                    jnp.asarray(table))
        np.testing.assert_allclose(
            np.asarray(k_view[:, :, :11]),
            np.asarray(dense_cache["k"][:, 0, :, :11]),
            rtol=1e-6, atol=1e-6)


class TestContinuousBatching:
    def test_mixed_lengths_match_solo_references(self):
        """The killer property: 10 mixed-length requests squeezed
        through 3 slots — admitted mid-flight, recycling retired slots'
        blocks — each emit exactly their SOLO dense-path stream."""
        from kubeshare_tpu.models.decoding import greedy_decode
        from kubeshare_tpu.serving import Request

        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        rng = np.random.default_rng(3)
        # 7 requests over 3 slots; lengths chosen to hit full-chunk,
        # ragged-tail, and short-pad prefill plans (repeated (L, new)
        # pairs keep the dense-reference compile count down — tier-1
        # time is compile-dominated at this model size)
        shapes = [(1, 3), (5, 8), (13, 4), (21, 11), (5, 8), (13, 4),
                  (29, 2)]
        reqs = [(f"r{i}", rng.integers(0, 64, length), new)
                for i, (length, new) in enumerate(shapes)]
        engine = _engine(params, config)
        for rid, prompt, new in reqs:
            engine.submit(Request(rid, prompt, new))
        out = engine.run()
        for rid, prompt, new in reqs:
            ref = np.asarray(greedy_decode(
                params, config, jnp.asarray(prompt, jnp.int32)[None], new))[0]
            assert out[rid].tokens == list(ref), rid
        # every retired request's blocks went home: refcounts all dropped,
        # and each block is either free or parked in the prefix cache's
        # idle pool (evictable on demand — still admission-fundable)
        assert engine.allocator.blocks_in_use == 0
        assert (engine.allocator.free_blocks
                + engine.allocator.cached_idle_blocks
                == engine.allocator.num_blocks - 1)
        assert engine.allocator.available_blocks == engine.allocator.num_blocks - 1
        # a live-loop server evicts completed results instead of letting
        # the result map grow with every request ever served
        popped = engine.pop_finished()
        assert sorted(popped) == sorted(rid for rid, _, _ in reqs)
        assert engine.pop_finished() == {}
        # and the pool was actually oversubscribed: peak in-use is under
        # what 10 requests would need simultaneously
        total_demand = sum(
            engine.allocator.blocks_for_tokens(len(p) + n)
            for _, p, n in reqs)
        assert 0 < engine.peak_blocks_in_use < total_demand

    def test_admission_waits_on_block_exhaustion(self):
        """A request the pool can't fund YET queues (no clamp, no drop)
        and admits after a retirement frees blocks; a request that can
        NEVER fit fails loudly at submit."""
        from kubeshare_tpu.serving import BlockExhausted, Request

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        # 6 allocatable blocks x 4 = 24 rows total
        engine = _engine(params, config, num_slots=2, num_blocks=7,
                         max_request_len=32)
        prompt = np.zeros(17, np.int32)  # 17 + 3 -> 5 blocks each
        engine.submit(Request("big0", prompt, 3))
        engine.submit(Request("big1", prompt, 3))
        engine.step()  # admits big0 (5 blocks); big1 (5 > 3 free) waits
        assert engine.result("big0").admitted_at is not None
        assert engine.result("big1").admitted_at is None
        out = engine.run()  # big0 retires -> big1 admits and completes
        assert len(out["big1"].tokens) == 3
        with pytest.raises(BlockExhausted, match="NEVER"):
            engine.submit(Request("huge", np.zeros(30, np.int32), 2))

    def test_submit_validation_is_loud(self):
        from kubeshare_tpu.serving import Request

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        engine = _engine(params, config)
        with pytest.raises(ValueError, match="max_new_tokens"):
            engine.submit(Request("a", np.zeros(4, np.int32), 0))
        with pytest.raises(ValueError, match="max_request_len"):
            engine.submit(Request("b", np.zeros(40, np.int32), 20))
        with pytest.raises(ValueError, match="rng"):
            engine.submit(Request("c", np.zeros(4, np.int32), 2,
                                  temperature=0.7))
        with pytest.raises(ValueError, match="non-empty"):
            engine.submit(Request("d", np.zeros(0, np.int32), 2))

    def test_short_pool_caps_pad_bucket(self):
        """A max_request_len below the prefill bucket must not reject a
        request that actually fits (review regression): prompt 17 +
        3 new = 20 rows in a 24-row bound with chunk 32 used to be
        refused over the uncapped 32-row pad bucket."""
        from kubeshare_tpu.models.decoding import greedy_decode
        from kubeshare_tpu.serving import Request

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        engine = _engine(params, config, num_slots=2, num_blocks=15,
                         max_request_len=24, prefill_chunk=32)
        prompt = np.asarray(jax.random.randint(
            jax.random.PRNGKey(5), (17,), 0, 64), np.int32)
        engine.warmup()
        baseline = engine.compile_counts()
        engine.submit(Request("r0", prompt, 3))
        out = engine.run()["r0"]
        ref = np.asarray(greedy_decode(
            params, config, jnp.asarray(prompt)[None], 3))[0]
        assert out.tokens == list(ref)
        # the capped (non-power-of-two) pad width was part of warmup
        assert engine.compile_counts() == baseline

    def test_eos_retires_early_and_frees_blocks(self):
        from kubeshare_tpu.models.decoding import greedy_decode
        from kubeshare_tpu.serving import Request

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        prompt = np.asarray(jax.random.randint(
            jax.random.PRNGKey(1), (9,), 0, 64), np.int32)
        ref = [int(t) for t in np.asarray(greedy_decode(
            params, config, jnp.asarray(prompt)[None], 8))[0]]
        eos = ref[2]  # the 3rd greedy token becomes "EOS"
        engine = _engine(params, config, eos_token=eos)
        engine.submit(Request("r0", prompt, 8))
        out = engine.run()["r0"]
        # stops AT the stream's first eos occurrence (which may precede
        # index 2 if the token repeats), mid-decode-span included
        assert out.tokens == ref[: ref.index(eos) + 1]
        assert len(out.tokens) < len(ref)
        assert engine.allocator.blocks_in_use == 0

    def test_zero_recompilation_after_warmup(self):
        """The acceptance criterion, asserted via jit cache stats: after
        warmup, a full mixed ragged workload adds ZERO compilations, and
        the prefill widths stay within the O(log chunk) bucket bound."""
        import math

        from kubeshare_tpu.serving import Request

        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        engine = _engine(params, config)
        engine.warmup()
        baseline = engine.compile_counts()
        chunk = engine.engine_config.prefill_chunk
        # widths bucketed to powers of two, lane counts to {1, num_slots}
        assert baseline["prefill"] <= 2 * (int(math.log2(chunk)) + 1)
        assert baseline["decode"] == 1
        rng = np.random.default_rng(5)
        for i in range(8):  # every remainder class over two waves
            engine.submit(Request(
                f"r{i}", rng.integers(0, 64, 2 * chunk + 1 + i),
                int(rng.integers(1, 6))))
        engine.run()
        assert engine.compile_counts() == baseline

    def test_engine_charges_through_guard(self):
        """Fractional-chip integration: every prefill chunk / decode
        step / first-token pick acquires and charges the token guard."""
        from kubeshare_tpu.isolation.guard import ExecutionGuard
        from kubeshare_tpu.serving import EngineConfig, Request, ServingEngine

        class FakeClient:
            def __init__(self):
                self.acquired = 0
                self.released_ms = 0.0

            def acquire(self, estimate_ms):
                self.acquired += 1
                return 1e9  # one grant funds the whole run

            def release(self, used_ms):
                self.released_ms += used_ms

        client = FakeClient()
        guard = ExecutionGuard(client=client, from_env=False,
                               idle_release_ms=0)
        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        engine = ServingEngine(
            params, config,
            EngineConfig(num_slots=2, block_size=4, num_blocks=17,
                         max_request_len=32, prefill_chunk=8),
            guard=guard)
        engine.submit(Request("r0", np.zeros(9, np.int32), 4))
        engine.run()
        assert client.acquired >= 1
        assert guard.total_gated_ms > 0.0
        # run() returned the held token at drain
        assert client.released_ms > 0.0


class TestPrefixCache:
    """The tentpole's contract: prefix-cached serving emits EXACTLY the
    streams the cache-disabled engine (and the dense reference) emits —
    across GQA/windowed/MoE/sampled configs, with shared blocks
    refcounted, mid-block divergence copied-on-write, and eviction
    deferred until a reservation would otherwise fail."""

    def _run_sequentially(self, engine, reqs):
        """Submit+drain one at a time so earlier requests' blocks are
        in the cache before later lookups (live traffic's steady state)."""
        from kubeshare_tpu.serving import Request

        out = {}
        for req in reqs:
            engine.submit(Request(**req))
            out.update({rid: r.tokens for rid, r in engine.run().items()
                        if r.done})
            engine.pop_finished()
        return out

    def test_streams_bit_exact_with_cache_disabled_across_configs(self):
        """Cache on vs cache off, token for token — full-block reuse,
        mid-block CoW divergence, and a fully cached prompt, under every
        attention variant the dense oracle covers."""
        cases = {
            "gqa_rope": dict(n_kv_heads=2, positional="rope"),
            "windowed": dict(attention_window=6),
            "moe": dict(moe_every=2, moe_num_experts=4, moe_top_k=2),
        }
        rng = np.random.default_rng(11)
        base = rng.integers(0, 64, 21)  # 5 full blocks (bs 4) + 1 token
        diverge = base.copy()
        diverge[18] = (diverge[18] + 1) % 64  # mid-block divergence
        reqs = [
            dict(rid="cold", prompt=base, max_new_tokens=6),
            dict(rid="exact", prompt=base.copy(), max_new_tokens=4),
            dict(rid="cow", prompt=diverge, max_new_tokens=6),
            dict(rid="short", prompt=base[:10].copy(), max_new_tokens=3),
        ]
        for name, extra in cases.items():
            config = _small_config(**extra)
            params = transformer_init(jax.random.PRNGKey(0), config)
            cached = _engine(params, config)
            plain = _engine(params, config, prefix_cache=False)
            got = self._run_sequentially(cached, reqs)
            want = self._run_sequentially(plain, reqs)
            assert got == want, name
            assert cached.prefix_hit_tokens > 0, name
            assert cached.cow_copies >= 1, name  # the divergence copied
            assert plain.prefix_hit_tokens == 0

    def test_sampled_streams_bit_exact_with_prefix_hits(self):
        """The key schedule must survive a cache hit: a sampled request
        admitted onto a matched prefix reproduces its solo stream."""
        from kubeshare_tpu.models.decoding import sample_decode

        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        prompt = np.asarray(jax.random.randint(
            jax.random.PRNGKey(3), (14,), 0, 64), np.int32)
        rng = jax.random.PRNGKey(9)
        reqs = [
            dict(rid="warm", prompt=prompt, max_new_tokens=3),
            dict(rid="samp", prompt=prompt.copy(), max_new_tokens=5,
                 temperature=0.8, rng=rng),
        ]
        engine = _engine(params, config, top_k=10, top_p=0.95)
        got = self._run_sequentially(engine, reqs)
        assert engine.prefix_hit_tokens == 13  # prompt-1 cap
        ref = np.asarray(sample_decode(
            params, config, jnp.asarray(prompt)[None], rng, 5,
            temperature=0.8, top_k=10, top_p=0.95))[0]
        assert got["samp"] == list(ref)

    def test_cow_divergence_does_not_corrupt_cached_prefix(self):
        """The corruption a CoW exists to prevent: after a diverging
        request appends into (a copy of) the shared tail block, the
        ORIGINAL cached stream must still replay exactly."""
        from kubeshare_tpu.models.decoding import greedy_decode

        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        rng = np.random.default_rng(4)
        a = rng.integers(0, 64, 10)  # bs 4: 2 full blocks + 2-token tail
        b = a.copy()
        b[9] = (b[9] + 7) % 64  # diverges at the tail block's 2nd row
        engine = _engine(params, config)
        got = self._run_sequentially(engine, [
            dict(rid="a1", prompt=a, max_new_tokens=6),
            dict(rid="b", prompt=b, max_new_tokens=6),
            dict(rid="a2", prompt=a.copy(), max_new_tokens=6),
        ])
        assert engine.cow_copies >= 1
        for rid, prompt in (("a1", a), ("b", b), ("a2", a)):
            ref = np.asarray(greedy_decode(
                params, config, jnp.asarray(prompt, jnp.int32)[None], 6))[0]
            assert got[rid] == list(ref), rid
        assert got["a1"] == got["a2"]

    def test_eviction_only_when_reserve_would_fail(self):
        """Cached blocks survive admissions the free list can fund and
        are drained (LRU) exactly when a reservation would otherwise
        raise BlockExhausted."""
        from kubeshare_tpu.serving import Request

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        # 12 allocatable blocks x 4 rows = 48 rows
        engine = _engine(params, config, num_slots=1, num_blocks=13,
                         max_request_len=32)
        rng = np.random.default_rng(7)
        engine.submit(Request("r0", rng.integers(0, 64, 13), 3))  # 4 blocks
        engine.run()
        cached_after_r0 = engine.allocator.cached_idle_blocks
        assert cached_after_r0 == 4  # 3 full + partial tail, all idle now
        # 8 free blocks fund this without touching the cache
        engine.submit(Request("r1", rng.integers(0, 64, 17), 3))  # 5 blocks
        engine.run()
        assert engine.allocator.evicted_blocks == 0
        assert engine.allocator.cached_idle_blocks > cached_after_r0
        # free list now 3; this needs 8 -> the LRU pool must drain
        engine.submit(Request("r2", rng.integers(0, 64, 29), 3))
        engine.run()
        assert engine.allocator.evicted_blocks > 0
        assert engine.allocator.blocks_in_use == 0
        assert (engine.allocator.free_blocks
                + engine.allocator.cached_idle_blocks
                == engine.allocator.num_blocks - 1)

    def test_exhaustion_with_inflight_decodes_keeps_slots_intact(self):
        """Regression (satellite): BlockExhausted at admission with
        decodes in flight must not disturb running slots; the queued
        request stays pending and admits once retirement frees blocks —
        with the cache, after LRU eviction — and still emits its solo
        reference stream."""
        from kubeshare_tpu.models.decoding import greedy_decode
        from kubeshare_tpu.serving import Request

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        rng = np.random.default_rng(9)
        # 12 allocatable blocks; r0/r1 take 6 each -> r2 (7) must wait
        engine = _engine(params, config, num_slots=3, num_blocks=13,
                         max_request_len=32)
        p0 = rng.integers(0, 64, 17)  # 17+6=23 rows -> 6 blocks
        p1 = rng.integers(0, 64, 18)  # 18+6=24 rows -> 6 blocks
        p2 = rng.integers(0, 64, 21)  # 21+6=27 rows -> 7 blocks
        engine.submit(Request("r0", p0, 6))
        engine.submit(Request("r1", p1, 6))
        engine.submit(Request("r2", p2, 6))
        # drive until r0 and r1 are BOTH decoding with r2 still queued
        while (engine.result("r0").first_token_at is None
               or engine.result("r1").first_token_at is None):
            assert engine.step()
        assert engine.result("r0").admitted_at is not None
        assert engine.result("r1").admitted_at is not None
        assert engine.result("r2").admitted_at is None  # pending, not lost
        assert engine.allocator.free_blocks == 0
        out = engine.run()  # a retirement funds r2 (eviction included)
        assert engine.allocator.evicted_blocks > 0
        for rid, prompt in (("r0", p0), ("r1", p1), ("r2", p2)):
            ref = np.asarray(greedy_decode(
                params, config, jnp.asarray(prompt, jnp.int32)[None], 6))[0]
            assert out[rid].tokens == list(ref), rid

    def test_zero_recompiles_with_cache_hits_and_cow(self):
        """Acceptance criterion: warmup covers everything the cache can
        dispatch — matched-prefix prefills at arbitrary start positions,
        the CoW copy, eviction-funded admissions — so a shared-prefix
        workload adds ZERO compiled shapes."""
        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        engine = _engine(params, config)
        engine.warmup()
        baseline = engine.compile_counts()
        assert baseline["copy"] == 1  # the cache's single extra shape
        rng = np.random.default_rng(6)
        shared = rng.integers(0, 64, 19)
        reqs = [dict(rid="cold", prompt=shared, max_new_tokens=4)]
        for i in range(6):  # full hits, mid-block CoW, ragged suffixes
            prompt = np.concatenate(
                [shared[: 11 + i], rng.integers(0, 64, 2 + i)])
            reqs.append(dict(rid=f"r{i}", prompt=prompt,
                             max_new_tokens=3 + i % 3))
        self._run_sequentially(engine, reqs)
        assert engine.prefix_hit_requests > 0 and engine.cow_copies > 0
        assert engine.compile_counts() == baseline

    def test_metrics_endpoint_scrapes_serving_plane(self):
        """Satellite: the engine exports its runtime counters through
        the same promtext textfile server the token daemons use — a
        stock Prometheus scrape, parsed back with the house parser."""
        import urllib.request

        from kubeshare_tpu.utils.promtext import parse_text

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        engine = _engine(params, config)
        rng = np.random.default_rng(2)
        shared = rng.integers(0, 64, 12)
        self._run_sequentially(engine, [
            dict(rid="m0", prompt=shared, max_new_tokens=4),
            dict(rid="m1", prompt=shared.copy(), max_new_tokens=3),
        ])
        server = engine.serve_metrics(port=0)
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/kubeshare-serving",
                timeout=5).read().decode()
        finally:
            server.stop()
        samples = {(s.name, tuple(sorted(s.labels.items()))): s.value
                   for s in parse_text(body)}
        req = "kubeshare_serving_requests_total"
        assert samples[(req, (("stage", "admitted"),))] == 2
        assert samples[(req, (("stage", "finished"),))] == 2
        assert samples[("kubeshare_serving_prefix_hit_tokens_total",
                        ())] == 11  # m1 matched prompt-1 tokens
        blocks = {k[1][0][1]: v for k, v in samples.items()
                  if k[0] == "kubeshare_serving_kv_blocks"}
        assert blocks["in_use"] == 0
        assert (blocks["free"] + blocks["cached"]
                == engine.allocator.num_blocks - 1)
        # histogram: every finished request's TTFT observed
        assert samples[("kubeshare_serving_ttft_seconds_count", ())] == 2
        assert samples[("kubeshare_serving_ttft_seconds_bucket",
                        (("le", "+Inf"),))] == 2


class TestPrefillPlan:
    """Satellite: plan_prefill_chunks edge cases — the exact prompt
    geometries a block-paged admission path must not fumble."""

    def test_one_token_prompt(self):
        from kubeshare_tpu.serving import plan_prefill_chunks

        plan, cover = plan_prefill_chunks(1, 8, 48)
        assert plan == [(0, 1, 0)] and cover == 1

    def test_prompt_shorter_than_one_block(self):
        from kubeshare_tpu.serving import plan_prefill_chunks

        # 3 tokens, chunk 8 -> one bucketed pad-forward chunk of width 4
        plan, cover = plan_prefill_chunks(3, 8, 48)
        assert plan == [(0, 4, 2)] and cover == 4

    def test_prompt_exact_chunk_multiple(self):
        from kubeshare_tpu.serving import plan_prefill_chunks

        plan, cover = plan_prefill_chunks(16, 8, 48)
        assert plan == [(0, 8, 7), (8, 8, 7)] and cover == 16

    def test_start_offset_plans_suffix_only(self):
        from kubeshare_tpu.serving import plan_prefill_chunks

        # matched 16 of 21: one bucketed tail sliding back to end at 20
        plan, cover = plan_prefill_chunks(21, 8, 48, start=16)
        assert plan == [(13, 8, 7)] and cover == 21
        # matched 16 of 17: a single width-1 chunk at the last token
        plan, cover = plan_prefill_chunks(17, 8, 48, start=16)
        assert plan == [(16, 1, 0)] and cover == 17
        with pytest.raises(ValueError, match="start"):
            plan_prefill_chunks(8, 8, 48, start=8)

    def test_edge_prompts_add_no_compiled_shapes(self):
        """Engine-level lock: 1-token, sub-block, and exact-multiple
        prompts all ride warmup's bucketed widths — zero new compiles
        across all three."""
        from kubeshare_tpu.serving import Request

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        engine = _engine(params, config)  # block_size 4, chunk 8
        engine.warmup()
        baseline = engine.compile_counts()
        rng = np.random.default_rng(8)
        for i, length in enumerate((1, 3, 16)):
            engine.submit(Request(f"e{i}", rng.integers(0, 64, length), 2))
        out = engine.run()
        assert all(len(r.tokens) == 2 for r in out.values())
        assert engine.compile_counts() == baseline


class TestRaggedPrefill:
    """Satellite: prefill_chunked accepts non-tiling prompts via
    power-of-two bucketed final chunks."""

    def test_matches_bulk_across_remainders(self):
        from kubeshare_tpu.models.decoding import prefill, prefill_chunked

        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        # short-pad, pow2, ragged-with-full-chunks, exact-tile, long-ragged
        for length in (3, 8, 11, 16, 21):
            prompt = jax.random.randint(
                jax.random.PRNGKey(length), (2, length), 0, 64)
            cache_b, logits_b = prefill(params, config, prompt)
            cache_c, logits_c = prefill_chunked(params, config, prompt, 8)
            np.testing.assert_allclose(
                np.asarray(logits_c), np.asarray(logits_b),
                rtol=2e-4, atol=2e-4, err_msg=f"L={length}")
            np.testing.assert_allclose(
                np.asarray(cache_c["k"]), np.asarray(cache_b["k"]),
                rtol=2e-4, atol=2e-4, err_msg=f"L={length}")
            np.testing.assert_allclose(
                np.asarray(cache_c["v"]), np.asarray(cache_b["v"]),
                rtol=2e-4, atol=2e-4, err_msg=f"L={length}")
            assert int(cache_c["length"]) == length

    def test_compile_count_bounded_by_buckets(self):
        """Compile-count regression: across EVERY remainder the chunk
        widths hitting the compiler stay within {chunk} + powers of two
        — O(log chunk) shapes, not one per remainder."""
        import math

        from kubeshare_tpu.models import decoding

        config = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=1, d_ff=64,
            max_seq_len=64, dtype=jnp.float32, attention="reference")
        params = transformer_init(jax.random.PRNGKey(0), config)
        chunk = 8
        widths = set()
        real = decoding._decode_chunk

        def recording(params, config, cache, tokens, *args, **kwargs):
            widths.add(int(tokens.shape[1]))
            return real(params, config, cache, tokens, *args, **kwargs)

        try:
            decoding._decode_chunk = recording
            for length in range(1, 2 * chunk + 1):
                prompt = jnp.zeros((1, length), jnp.int32)
                decoding.prefill_chunked(params, config, prompt, chunk)
        finally:
            decoding._decode_chunk = real
        allowed = {chunk} | {2 ** i for i in range(int(math.log2(chunk)) + 1)}
        assert widths <= allowed, widths
        assert len(widths) <= int(math.log2(chunk)) + 1

    def test_bucket_capped_at_max_seq_len(self):
        """A non-power-of-two max_seq_len below the bucket must not make
        the pad-forward chunk overrun the cache (review regression):
        prompt 17 in a 20-row cache with chunk 32 bucketed to 32 used to
        crash in XLA."""
        from kubeshare_tpu.models.decoding import prefill, prefill_chunked
        from kubeshare_tpu.models.transformer import (
            TransformerConfig, transformer_init)

        config = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=1, d_ff=64,
            max_seq_len=20, dtype=jnp.float32, attention="reference")
        params = transformer_init(jax.random.PRNGKey(0), config)
        prompt = jax.random.randint(jax.random.PRNGKey(4), (1, 17), 0, 64)
        cache_b, logits_b = prefill(params, config, prompt)
        cache_c, logits_c = prefill_chunked(params, config, prompt, 32)
        np.testing.assert_allclose(
            np.asarray(logits_c), np.asarray(logits_b),
            rtol=2e-4, atol=2e-4)
        assert int(cache_c["length"]) == 17

    def test_bucket_width(self):
        from kubeshare_tpu.models.decoding import bucket_width

        assert [bucket_width(r, 8) for r in (1, 2, 3, 4, 5, 7, 8)] == [
            1, 2, 4, 4, 8, 8, 8]
        with pytest.raises(ValueError):
            bucket_width(0, 8)
        with pytest.raises(ValueError):
            bucket_width(9, 8)


class TestQoSFairQueue:
    """Satellite/tentpole unit layer: the decayed virtual-time fair
    queue must mirror tokend's share model — Guarantee strictly first,
    lowest decayed service per unit weight within a class, FIFO within
    a tenant, exponential recovery while idle."""

    def _registry(self):
        from kubeshare_tpu.serving import (QOS_OPPORTUNISTIC,
                                           TenantRegistry, TenantSpec)

        return TenantRegistry([
            TenantSpec("gold", weight=1.0),
            TenantSpec("silver", weight=2.0),
            TenantSpec("batch", qos_class=QOS_OPPORTUNISTIC),
        ])

    def test_class_then_weighted_service_order(self):
        from kubeshare_tpu.serving import FairQueue

        clock = [0.0]
        q = FairQueue(self._registry(), window_s=10.0,
                      clock=lambda: clock[0])
        for t in ("gold", "silver", "batch"):
            q.push(t, f"{t}-req")
        # untouched counters: guarantee tenants first, FIFO tie-break
        assert q.order() == ["gold", "silver", "batch"]
        # equal raw service, but silver's weight 2 halves its normalized
        # share -> silver overtakes gold; batch stays last regardless
        q.charge("gold", 100)
        q.charge("silver", 100)
        q.charge("batch", 1)
        assert q.order() == ["silver", "gold", "batch"]
        # an opportunistic tenant with ZERO service still never ranks
        # above a guarantee tenant (the scheduler's priority-first Less)
        assert q.normalized_service("batch") < q.normalized_service("gold")

    def test_decay_recovers_share(self):
        import math

        from kubeshare_tpu.serving import FairQueue

        clock = [0.0]
        q = FairQueue(self._registry(), window_s=10.0,
                      clock=lambda: clock[0])
        q.charge("gold", 80)
        assert q.normalized_service("gold") == pytest.approx(80)
        clock[0] = 10.0  # one window later: service decays to 1/e
        assert q.normalized_service("gold") == pytest.approx(
            80 * math.exp(-1))
        clock[0] = 100.0  # ten windows: effectively forgiven
        assert q.normalized_service("gold") < 0.01

    def test_fifo_within_tenant_and_requeue_front(self):
        from kubeshare_tpu.serving import FairQueue

        q = FairQueue(self._registry())
        q.push("gold", "a")
        q.push("gold", "b")
        assert q.peek("gold") == "a"
        q.requeue_front("gold", "resumed")
        assert q.pop("gold") == "resumed"
        assert q.pop("gold") == "a"
        assert q.pop("gold") == "b"
        assert len(q) == 0 and not q

    def test_unknown_tenant_is_loud(self):
        from kubeshare_tpu.serving import FairQueue

        q = FairQueue(self._registry())
        with pytest.raises(KeyError, match="unknown tenant"):
            q.push("nope", "x")


class TestQoSPreemption:
    """The tentpole's contract: a Guarantee admission the pool cannot
    fund preempts an Opportunistic decode slot, the victim's blocks
    retire into the prefix index, and the victim RESUMES from its first
    uncached token emitting EXACTLY its unpreempted stream — greedy and
    sampled — with zero new compiled shapes."""

    def _registry(self, quota=None):
        from kubeshare_tpu.serving import (QOS_OPPORTUNISTIC,
                                           TenantRegistry, TenantSpec)

        return TenantRegistry([
            TenantSpec("gold"),
            TenantSpec("batch", qos_class=QOS_OPPORTUNISTIC,
                       kv_block_quota=quota),
        ])

    def _engine(self, params, config, registry, **overrides):
        from kubeshare_tpu.serving import EngineConfig, ServingEngine

        kwargs = dict(num_slots=2, block_size=4, num_blocks=13,
                      max_request_len=32, prefill_chunk=8)
        kwargs.update(overrides)
        return ServingEngine(params, config, EngineConfig(**kwargs),
                             tenants=registry)

    def _drive_to_decode(self, engine, rid, min_tokens=2):
        """Step until request ``rid`` is decoding with >= min_tokens
        emitted (so a preemption lands mid-stream, not at a boundary)."""
        while True:
            r = engine.result(rid)
            if (r.first_token_at is not None and not r.done
                    and len([s for s in engine._slots if s.rid == rid
                             and s.state == "decode"])
                    and len([s for s in engine._slots
                             if s.rid == rid][0].generated) >= min_tokens):
                return
            assert engine.step(), f"engine idle before {rid} decoded"

    def test_preempted_then_resumed_greedy_bit_exact(self):
        from kubeshare_tpu.models.decoding import greedy_decode
        from kubeshare_tpu.serving import Request

        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        registry = self._registry()
        engine = self._engine(params, config, registry)
        engine.warmup()
        baseline = engine.compile_counts()
        rng = np.random.default_rng(21)
        # the victim's decode must be LONG: with the pipelined step an
        # in-flight span is consumed before anyone is sacrificed, so a
        # victim that would finish in that span retires instead of
        # being preempted (the cheaper outcome, deliberately)
        p_batch = rng.integers(0, 64, 17)  # 17 + 14 = 31 rows -> 8 blocks
        p_gold = rng.integers(0, 64, 18)   # 18 + 6 = 24 rows -> 6 blocks
        engine.submit(Request("victim", p_batch, 14, tenant="batch"))
        self._drive_to_decode(engine, "victim")
        # 12-block pool: victim holds 8, gold needs 6 > 4 free -> the
        # Guarantee admission must preempt the Opportunistic decode
        engine.submit(Request("gold", p_gold, 6, tenant="gold"))
        out = engine.run()
        assert engine.preemptions.get("batch", 0) >= 1
        for rid, prompt, new in (("victim", p_batch, 14),
                                 ("gold", p_gold, 6)):
            ref = np.asarray(greedy_decode(
                params, config, jnp.asarray(prompt, jnp.int32)[None],
                new))[0]
            assert out[rid].tokens == list(ref), rid
        # the victim's resume actually hit the cache it was retired into
        assert engine.prefix_hit_requests >= 1
        # blocks all home, zero new compiled shapes (the acceptance bar)
        assert engine.allocator.blocks_in_use == 0
        assert engine.compile_counts() == baseline

    def test_preempted_then_resumed_sampled_bit_exact(self):
        """The key schedule must survive preemption: emission k of the
        original consumes step_keys[k-1], which becomes the resumed
        request's first key — same stream as the dense sampled oracle."""
        from kubeshare_tpu.models.decoding import sample_decode
        from kubeshare_tpu.serving import Request

        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        registry = self._registry()
        engine = self._engine(params, config, registry, top_k=10,
                              top_p=0.95)
        rng = np.random.default_rng(22)
        p_batch = rng.integers(0, 64, 17)  # 14 new: survives the
        p_gold = rng.integers(0, 64, 18)   # in-flight span (see greedy)
        key = jax.random.PRNGKey(13)
        engine.submit(Request("victim", p_batch, 14, temperature=0.8,
                              rng=key, tenant="batch"))
        self._drive_to_decode(engine, "victim")
        engine.submit(Request("gold", p_gold, 6, tenant="gold"))
        out = engine.run()
        assert engine.preemptions.get("batch", 0) >= 1
        ref = np.asarray(sample_decode(
            params, config, jnp.asarray(p_batch, jnp.int32)[None], key,
            14, temperature=0.8, top_k=10, top_p=0.95))[0]
        assert out["victim"].tokens == list(ref)

    def test_quota_exhaustion_denies_admission(self):
        """Satellite: a tenant at its KV-block quota queues (other
        tenants keep flowing — no head-of-line across tenants), admits
        once its own cached blocks drain, and a request that can NEVER
        fit the quota fails loudly at submit."""
        from kubeshare_tpu.serving import QuotaExceeded, Request

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        registry = self._registry(quota=6)
        engine = self._engine(params, config, registry, num_slots=3,
                              num_blocks=25)
        rng = np.random.default_rng(23)
        with pytest.raises(QuotaExceeded, match="NEVER"):
            # 25+3 rows -> 7 blocks > the 6-block quota
            engine.submit(Request("huge", rng.integers(0, 64, 25), 3,
                                  tenant="batch"))
        engine.submit(Request("b0", rng.integers(0, 64, 17), 3,
                              tenant="batch"))  # 5 blocks
        engine.submit(Request("b1", rng.integers(0, 64, 17), 3,
                              tenant="batch"))  # 5 more: over quota
        engine.submit(Request("g0", rng.integers(0, 64, 17), 3,
                              tenant="gold"))
        engine.step()
        # b0 admitted; b1 quota-blocked; gold NOT blocked behind it
        assert engine.result("b0").admitted_at is not None
        assert engine.result("b1").admitted_at is None
        assert engine.result("g0").admitted_at is not None
        assert engine.allocator.tenant_usage("batch") == 5
        out = engine.run()  # b0 retires -> its cached blocks drain ->
        assert len(out["b1"].tokens) == 3  # b1 fits its quota again
        assert engine.allocator.tenant_usage("batch") <= 6

    def test_quota_blocked_guarantee_does_not_preempt(self):
        """Review regression: a Guarantee head blocked on its OWN quota
        must not preempt — a victim's slot cannot cure a quota block,
        and preempting one Opportunistic decode per tick is a thrash
        loop.  The blocked head waits; the victim keeps decoding."""
        from kubeshare_tpu.serving import (QOS_OPPORTUNISTIC, Request,
                                           TenantRegistry, TenantSpec)

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        registry = TenantRegistry([
            TenantSpec("gold", kv_block_quota=6),
            TenantSpec("batch", qos_class=QOS_OPPORTUNISTIC),
        ])
        engine = self._engine(params, config, registry, num_slots=2,
                              num_blocks=25)
        rng = np.random.default_rng(26)
        engine.submit(Request("g0", rng.integers(0, 64, 17), 6,
                              tenant="gold"))  # 6 blocks: quota full
        engine.submit(Request("victim", rng.integers(0, 64, 9), 20,
                              tenant="batch"))
        engine.submit(Request("g1", rng.integers(0, 64, 17), 3,
                              tenant="gold"))  # 5 blocks: quota-blocked
        for _ in range(6):
            engine.step()
        # the quota-blocked gold head never preempted the batch decode
        assert engine.preemptions.get("batch", 0) == 0
        assert engine.result("g1").admitted_at is None
        out = engine.run()  # g0 retires -> gold's cache drains -> g1 fits
        assert engine.preemptions.get("batch", 0) == 0
        assert len(out["g1"].tokens) == 3
        assert len(out["victim"].tokens) == 20

    def test_quota_exact_request_readmits_through_own_cache(self):
        """Review regression (livelock): a request sized EXACTLY to its
        tenant's quota, re-submitted after retiring (so admission takes
        a mid-block prefix hit on its own cached chain), must not wedge
        — the hit path pins the retained chain + CoW source past the
        quota, so admission falls back to a COLD reserve that may evict
        the chain.  Streams stay correct either way."""
        from kubeshare_tpu.models.decoding import greedy_decode
        from kubeshare_tpu.serving import (QOS_OPPORTUNISTIC, Request,
                                           TenantRegistry, TenantSpec)

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        registry = TenantRegistry([
            TenantSpec("gold"),
            # 14 + 2 = 16 rows = 4 blocks: exactly the quota
            TenantSpec("batch", qos_class=QOS_OPPORTUNISTIC,
                       kv_block_quota=4),
        ])
        engine = self._engine(params, config, registry)
        rng = np.random.default_rng(27)
        prompt = rng.integers(0, 64, 14)  # match will end mid-block (13)
        engine.submit(Request("b0", prompt, 2, tenant="batch"))
        out0 = engine.run()
        engine.submit(Request("b1", prompt.copy(), 2, tenant="batch"))
        out1 = engine.run()  # must terminate (cold fallback), not spin
        ref = np.asarray(greedy_decode(
            params, config, jnp.asarray(prompt, jnp.int32)[None], 2))[0]
        assert out0["b0"].tokens == list(ref)
        assert out1["b1"].tokens == list(ref)
        assert engine.allocator.tenant_usage("batch") <= 4

    def test_doomed_quota_reserve_keeps_cache(self):
        """Review regression: a reservation the quota can NEVER fit
        (blocked by IN-USE blocks, not cache) must raise without
        draining the tenant's idle-cached blocks — the no-wipe
        discipline the pool-level doomed-check already has."""
        from kubeshare_tpu.serving import BlockAllocator, QuotaExceeded

        alloc = BlockAllocator(num_blocks=12, block_size=4)  # 11 usable
        held = alloc.reserve(7, "live", tenant="t", quota=10)  # in use
        cached = alloc.reserve(3, "old", tenant="t", quota=10)
        alloc.mark_cached(cached)
        alloc.reclaim(cached)  # 3 idle-cached, still charged
        assert alloc.cached_idle_blocks == 3
        with pytest.raises(QuotaExceeded, match="full own-cache drain"):
            alloc.reserve(5, "doomed", tenant="t", quota=10)
        # the doomed attempt did not evict a single cached block
        assert alloc.cached_idle_blocks == 3
        assert alloc.evicted_blocks == 0
        assert alloc.tenant_usage("t") == 10
        alloc.reclaim(held)

    def test_guarantee_reclaims_opportunistic_cached_blocks(self):
        """Satellite regression: idle-cached blocks charged to an
        Opportunistic tenant are the FIRST evicted when a Guarantee
        reservation needs the HBM — and the charge moves off the
        Opportunistic tenant's quota ledger."""
        from kubeshare_tpu.models.decoding import greedy_decode
        from kubeshare_tpu.serving import Request

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        registry = self._registry()
        engine = self._engine(params, config, registry, num_slots=1)
        rng = np.random.default_rng(24)
        p0 = rng.integers(0, 64, 21)  # 21+3 -> 6 blocks
        engine.submit(Request("b0", p0, 3, tenant="batch"))
        engine.run()
        assert engine.allocator.cached_idle_blocks == 6
        assert engine.allocator.tenant_usage("batch") == 6
        # gold needs 8 blocks; only 6 free -> must evict batch's cache
        p1 = rng.integers(0, 64, 29)  # 29+3 -> 8 blocks
        engine.submit(Request("g0", p1, 3, tenant="gold"))
        out = engine.run()
        assert engine.allocator.evicted_blocks > 0
        assert engine.allocator.tenant_usage("batch") < 6
        ref = np.asarray(greedy_decode(
            params, config, jnp.asarray(p1, jnp.int32)[None], 3))[0]
        assert out["g0"].tokens == list(ref)

    def test_allocator_evicts_preferred_tenants_first(self):
        """Allocator-level lock for the class asymmetry: with
        evict_tenants_first, the drain skips colder blocks charged to
        other tenants and takes the preferred victim's instead."""
        from kubeshare_tpu.serving import BlockAllocator

        alloc = BlockAllocator(num_blocks=6, block_size=4)  # 5 usable
        a = alloc.reserve(2, "a", tenant="gold")
        b = alloc.reserve(2, "b", tenant="batch")
        alloc.mark_cached(a + b)
        alloc.reclaim(a)  # gold's blocks idle FIRST -> colder in LRU
        alloc.reclaim(b)
        # plain LRU would evict gold's; the preference must pick batch's
        alloc.reserve(2, "c", tenant="gold",
                      evict_tenants_first={"batch"})
        assert alloc.tenant_usage("gold") >= 2  # gold's cache survived
        assert alloc.tenant_usage("batch") < 2
        assert alloc.evicted_blocks >= 1

    def test_quota_counts_idle_cached_blocks_and_own_drain(self):
        """Allocator-level quota semantics: idle-cached blocks stay on
        the tenant's ledger; a reservation over quota drains the
        tenant's OWN cache before raising."""
        from kubeshare_tpu.serving import BlockAllocator, QuotaExceeded

        alloc = BlockAllocator(num_blocks=9, block_size=4)  # 8 usable
        got = alloc.reserve(4, "a", tenant="t", quota=6)
        alloc.mark_cached(got)
        alloc.reclaim(got)  # all idle-cached, still charged
        assert alloc.tenant_usage("t") == 4
        # 4 cached + 4 new > 6 -> drains its own cache, then fits
        alloc.reserve(4, "b", tenant="t", quota=6)
        assert alloc.tenant_usage("t") <= 6
        with pytest.raises(QuotaExceeded):
            alloc.reserve(4, "c", tenant="t", quota=6)

    def test_qos_metrics_flow_through_collect_metrics(self):
        """Satellite: the per-tenant families ride the same promtext
        surface as everything else — queue depth, quota occupancy,
        tokens, preemptions, TTFT by class."""
        from kubeshare_tpu.serving import Request
        from kubeshare_tpu.utils.promtext import encode_families, parse_text

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        registry = self._registry()
        engine = self._engine(params, config, registry)
        rng = np.random.default_rng(25)
        engine.submit(Request("victim", rng.integers(0, 64, 17), 14,
                              tenant="batch"))
        self._drive_to_decode(engine, "victim")
        engine.submit(Request("gold", rng.integers(0, 64, 18), 6,
                              tenant="gold"))
        engine.run()
        samples = {(s.name, tuple(sorted(s.labels.items()))): s.value
                   for s in parse_text(
                       encode_families(engine.collect_metrics()))}
        assert samples[("kubeshare_serving_preemptions_total",
                        (("tenant", "batch"),))] >= 1
        assert samples[("kubeshare_serving_preemptions_total",
                        (("tenant", "gold"),))] == 0
        assert samples[("kubeshare_serving_tenant_tokens_total",
                        (("tenant", "gold"),))] == 6
        assert samples[("kubeshare_serving_tenant_tokens_total",
                        (("tenant", "batch"),))] == 14
        assert samples[("kubeshare_serving_tenant_queue_depth",
                        (("tenant", "batch"),))] == 0
        assert samples[("kubeshare_serving_tenant_kv_blocks",
                        (("tenant", "gold"),))] >= 0
        # TTFT by class: one guarantee and one opportunistic request
        assert samples[("kubeshare_serving_ttft_by_class_seconds_count",
                        (("qos", "guarantee"),))] == 1
        assert samples[("kubeshare_serving_ttft_by_class_seconds_count",
                        (("qos", "opportunistic"),))] == 1
        # TBT: every token after a request's first gets exactly ONE
        # inter-token observation — the preempted victim's resume gap
        # included (review regression: the stall from its last
        # pre-preemption token to the continuation's first is a real
        # inter-token gap and must not vanish from the histogram)
        assert samples[("kubeshare_serving_tbt_seconds_count",
                        (("qos", "guarantee"),))] == 6 - 1
        assert samples[("kubeshare_serving_tbt_seconds_count",
                        (("qos", "opportunistic"),))] == 14 - 1

    def test_unknown_tenant_rejected_at_submit(self):
        from kubeshare_tpu.serving import Request

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        engine = self._engine(params, config, self._registry())
        with pytest.raises(ValueError, match="unknown tenant"):
            engine.submit(Request("x", np.zeros(4, np.int32), 2,
                                  tenant="nope"))


class TestMixedBatching:
    """Tentpole contract: the fused mixed step (one budget-bounded
    prefill chunk riding the decode dispatch) emits EXACTLY the
    streams the either/or scheduler emits — across GQA/windowed/MoE,
    greedy and sampled, with prefix-cache CoW and QoS preemption in
    play — and adds zero compiled shapes after warmup."""

    def _pair(self, params, config, mixed, **overrides):
        from kubeshare_tpu.serving import EngineConfig, ServingEngine

        kwargs = dict(num_slots=3, block_size=4, num_blocks=41,
                      max_request_len=48, prefill_chunk=8, mixed=mixed)
        kwargs.update(overrides)
        return ServingEngine(params, config, EngineConfig(**kwargs))

    def _streams(self, engine, reqs):
        from kubeshare_tpu.serving import Request

        for req in reqs:
            engine.submit(Request(**req))
        return {rid: r.tokens for rid, r in engine.run().items()}

    def test_streams_bit_exact_mixed_on_vs_off_across_configs(self):
        """Mixed on vs off, token for token, same workload: long
        multi-chunk prompts prefilling while other lanes decode —
        exactly the coexistence the fused step handles.  The GQA case
        carries SAMPLED lanes too (the key schedule must survive
        fusion: lanes riding mixed dispatches consume exactly the keys
        the split dispatches would)."""
        cases = {
            "gqa_rope": dict(n_kv_heads=2, positional="rope"),
            "windowed": dict(attention_window=6),
            "moe": dict(moe_every=2, moe_num_experts=4, moe_top_k=2),
        }
        rng = np.random.default_rng(31)
        reqs = [
            dict(rid="long", prompt=rng.integers(0, 64, 29),
                 max_new_tokens=6),
            dict(rid="s0", prompt=rng.integers(0, 64, 5),
                 max_new_tokens=8),
            dict(rid="s1", prompt=rng.integers(0, 64, 13),
                 max_new_tokens=4),
            dict(rid="long2", prompt=rng.integers(0, 64, 21),
                 max_new_tokens=5),
        ]
        sampled = [
            dict(rid="samp_long", prompt=rng.integers(0, 64, 29),
                 max_new_tokens=6, temperature=0.8,
                 rng=jax.random.PRNGKey(41)),
            dict(rid="samp", prompt=rng.integers(0, 64, 13),
                 max_new_tokens=7, temperature=1.1,
                 rng=jax.random.PRNGKey(42)),
        ]
        for name, extra in cases.items():
            config = _small_config(**extra)
            params = transformer_init(jax.random.PRNGKey(0), config)
            workload = reqs + (sampled if name == "gqa_rope" else [])
            kwargs = (dict(top_k=10, top_p=0.95)
                      if name == "gqa_rope" else {})
            on = self._pair(params, config, mixed=True, **kwargs)
            off = self._pair(params, config, mixed=False, **kwargs)
            got = self._streams(on, workload)
            want = self._streams(off, workload)
            assert got == want, name
            # the fused path actually ran (and the control arm didn't)
            assert on.mixed_steps > 0, name
            assert off.mixed_steps == 0, name

    def test_cow_divergence_under_mixed(self):
        """Prefix-cache interaction: a mid-block CoW divergence whose
        prefill rides a mixed dispatch (another lane decoding) must
        not perturb either stream."""
        from kubeshare_tpu.serving import Request

        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        rng = np.random.default_rng(33)
        base = rng.integers(0, 64, 21)
        diverge = base.copy()
        diverge[18] = (diverge[18] + 1) % 64  # mid-block divergence
        bg_prompt = rng.integers(0, 64, 13)
        streams = {}
        for mixed in (True, False):
            engine = self._pair(params, config, mixed=mixed)
            engine.submit(Request("warm", base, 2))
            engine.run()  # retires -> base's blocks are in the trie
            engine.submit(Request("bg", bg_prompt, 12))
            for _ in range(4):  # bg reaches decode (same count both
                engine.step()   # arms: no coexistence yet)
            engine.submit(Request("cow", diverge, 6))
            out = engine.run()
            assert engine.cow_copies >= 1
            if mixed:
                assert engine.mixed_steps >= 1
            streams[mixed] = {rid: r.tokens for rid, r in out.items()}
        assert streams[True] == streams[False]

    def test_preemption_resume_under_mixed(self):
        """QoS interaction: cache-backed preemption and bit-exact
        resume survive mixed scheduling (the Guarantee admission's
        prefill fuses with the surviving Opportunistic decode).  The
        zero-new-shapes lock for preemption under a WARMED mixed
        engine lives in TestQoSPreemption (same discipline, 2 slots);
        this test adds the 3-slot shape where fusion runs DURING the
        preemption window."""
        from kubeshare_tpu.models.decoding import greedy_decode
        from kubeshare_tpu.serving import (QOS_OPPORTUNISTIC, EngineConfig,
                                           Request, ServingEngine,
                                           TenantRegistry, TenantSpec)

        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        registry = TenantRegistry([
            TenantSpec("gold"),
            TenantSpec("batch", qos_class=QOS_OPPORTUNISTIC),
        ])
        engine = ServingEngine(params, config, EngineConfig(
            num_slots=3, block_size=4, num_blocks=13,
            max_request_len=32, prefill_chunk=8), tenants=registry)
        rng = np.random.default_rng(34)
        # victims decode LONG (19 tokens): the pipelined consume runs
        # before anyone is sacrificed, so short victims would simply
        # retire and dodge the preemption this test locks
        p0 = rng.integers(0, 64, 5)   # 5 + 19 = 24 rows -> 6 blocks
        p1 = rng.integers(0, 64, 5)   # 6 more: the 12-block pool is full
        pg = rng.integers(0, 64, 10)  # 10 + 4 = 14 rows -> 4 blocks
        engine.submit(Request("v0", p0, 19, tenant="batch"))
        engine.submit(Request("v1", p1, 19, tenant="batch"))

        def both_decoding():
            slots = [s for s in engine._slots
                     if s.rid in ("v0", "v1")]
            return len(slots) == 2 and all(
                s.state == "decode" and len(s.generated) >= 2
                for s in slots)

        while not both_decoding():
            assert engine.step()
        engine.submit(Request("gold", pg, 4, tenant="gold"))
        out = engine.run()
        assert engine.preemptions.get("batch", 0) >= 1
        assert engine.mixed_steps >= 1  # gold's prefill rode a decode
        for rid, prompt, new in (("v0", p0, 19), ("v1", p1, 19),
                                 ("gold", pg, 4)):
            ref = np.asarray(greedy_decode(
                params, config, jnp.asarray(prompt, jnp.int32)[None],
                new))[0]
            assert out[rid].tokens == list(ref), rid
        assert engine.allocator.blocks_in_use == 0

    def test_mixed_budget_bounds_fused_chunk(self):
        """mixed_prefill_budget bounds the prefill tokens fused per
        step: full-width chunks are sliced to power-of-two pieces at
        or under the budget (never a new compiled shape), and streams
        still match the dense oracle."""
        from kubeshare_tpu.models.decoding import greedy_decode
        from kubeshare_tpu.serving import Request

        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        engine = self._pair(params, config, mixed=True,
                            mixed_prefill_budget=4)
        rng = np.random.default_rng(35)
        bg_prompt = rng.integers(0, 64, 5)
        long_prompt = rng.integers(0, 64, 29)
        fused_widths = []
        orig = engine._mixed_step

        def recording(w, pk, pv, p_table, p_start, p_tokens, *rest):
            fused_widths.append(int(p_tokens.shape[1]))
            return orig(w, pk, pv, p_table, p_start, p_tokens, *rest)

        engine._mixed_step = recording
        engine.submit(Request("bg", bg_prompt, 14))
        for _ in range(3):
            engine.step()  # bg decoding before the long prompt lands
        engine.submit(Request("long", long_prompt, 3))
        out = engine.run()
        assert fused_widths and max(fused_widths) <= 4
        for rid, prompt, new in (("bg", bg_prompt, 14),
                                 ("long", long_prompt, 3)):
            ref = np.asarray(greedy_decode(
                params, config, jnp.asarray(prompt, jnp.int32)[None],
                new))[0]
            assert out[rid].tokens == list(ref), rid

    def test_sliced_remainder_stays_bucketed_after_decode_drain(self):
        """Review regression: slicing a wide chunk must leave only
        WARMED bucket widths in the plan (binary decomposition of the
        remainder) — if the decode pool drains mid-slice, the
        remainder dispatches standalone, and a raw width-minus-piece
        remainder (e.g. 12 of a 16-chunk at budget 4) would recompile
        after warmup."""
        from kubeshare_tpu.serving import Request

        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        engine = self._pair(params, config, mixed=True, num_slots=2,
                            prefill_chunk=16, mixed_prefill_budget=4)
        engine.warmup()
        baseline = engine.compile_counts()
        rng = np.random.default_rng(39)
        engine.submit(Request("bg", rng.integers(0, 64, 5), 6))
        for _ in range(2):
            engine.step()  # bg decoding, close to its budget
        # 32-token prompt: two 16-wide chunks, sliced at budget 4; bg
        # retires inside the first fused span, stranding the sliced
        # remainder for STANDALONE dispatch
        engine.submit(Request("long", rng.integers(0, 64, 32), 3))
        out = engine.run()
        assert engine.mixed_steps >= 1
        assert len(out["long"].tokens) == 3
        assert engine.compile_counts() == baseline

    def test_prefill_round_robin_rotation(self):
        """Satellite regression: step() used to always advance
        prefill[0], so a many-chunk prompt monopolized prefill ticks
        over later admissions — filling slots must rotate."""
        from kubeshare_tpu.serving import Request

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        engine = _engine(params, config, num_slots=2)
        rng = np.random.default_rng(36)
        # two 29-token prompts: 4 chunks each (chunk 8)
        engine.submit(Request("a", rng.integers(0, 64, 29), 2))
        engine.submit(Request("b", rng.integers(0, 64, 29), 2))
        engine.step()  # admits both, runs ONE chunk (slot a)
        engine.step()  # must advance slot b, not a again
        plans = {s.rid: len(s.plan) for s in engine._slots
                 if s.state == "prefill"}
        assert plans == {"a": 3, "b": 3}
        out = engine.run()
        assert all(len(r.tokens) == 2 for r in out.values())

    def test_tbt_histogram_and_mixed_dispatch_counter(self):
        """Satellite: the inter-token-latency histogram rides the
        promtext plane per QoS class, and dispatches_total grows a
        kind="mixed" series consistent with the standalone kinds."""
        from kubeshare_tpu.serving import Request
        from kubeshare_tpu.utils.promtext import encode_families, parse_text

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        engine = _engine(params, config)
        rng = np.random.default_rng(37)
        reqs = [("m0", rng.integers(0, 64, 21), 6),
                ("m1", rng.integers(0, 64, 9), 5),
                ("m2", rng.integers(0, 64, 13), 4)]
        for rid, prompt, new in reqs:
            engine.submit(Request(rid, prompt, new))
        engine.run()
        assert engine.mixed_steps >= 1
        samples = {(s.name, tuple(sorted(s.labels.items()))): s.value
                   for s in parse_text(
                       encode_families(engine.collect_metrics()))}
        # every token after a request's first came from a decode span
        # -> one TBT observation each (default tenant = guarantee)
        assert samples[("kubeshare_serving_tbt_seconds_count",
                        (("qos", "guarantee"),))] == sum(
            new - 1 for _, _, new in reqs)
        assert samples[("kubeshare_serving_tbt_seconds_count",
                        (("qos", "opportunistic"),))] == 0
        kinds = {k[1][0][1]: v for k, v in samples.items()
                 if k[0] == "kubeshare_serving_dispatches_total"}
        assert kinds["mixed"] == engine.mixed_steps
        assert kinds["prefill_chunk"] == \
            engine.prefill_chunks - engine.mixed_steps
        assert kinds["decode_span"] == \
            engine.decode_steps - engine.mixed_steps

    def test_dispatch_sync_is_guard_only(self):
        """Satellite regression (host/device overlap): an unguarded
        engine must NOT hard-sync per dispatch (the hot loop pipelines
        one step ahead and reads tokens when consumed); a guarded
        engine still syncs so measured wall time is charged."""
        from kubeshare_tpu.isolation.guard import ExecutionGuard
        from kubeshare_tpu.serving import Request

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        calls = {"n": 0}
        real = jax.block_until_ready

        def counting(x):
            calls["n"] += 1
            return real(x)

        rng = np.random.default_rng(38)
        prompt = rng.integers(0, 64, 9)
        engine = _engine(params, config)
        jax.block_until_ready = counting
        try:
            engine.submit(Request("r0", prompt, 4))
            engine.run()
        finally:
            jax.block_until_ready = real
        assert calls["n"] == 0  # unguarded: fully async dispatches

        class FakeClient:
            def acquire(self, estimate_ms):
                return 1e9

            def release(self, used_ms):
                pass

        from kubeshare_tpu.serving import EngineConfig, ServingEngine

        guard = ExecutionGuard(client=FakeClient(), from_env=False,
                               idle_release_ms=0)
        engine = ServingEngine(params, config, EngineConfig(
            num_slots=3, block_size=4, num_blocks=41,
            max_request_len=48, prefill_chunk=8), guard=guard)
        jax.block_until_ready = counting
        try:
            engine.submit(Request("r1", prompt, 4))
            engine.run()
        finally:
            jax.block_until_ready = real
        assert calls["n"] >= 1  # guarded: every dispatch synced...
        assert guard.total_gated_ms > 0.0  # ...and charged wall time


class TestKVTier:
    """KV cache tiering (serving/kv_tier.py): demoted blocks round-trip
    the wire format bit-identically, tier-on streams are bit-exact with
    tier-off across attention variants and sampling, the tenant quota
    ledger uncharges on demotion / re-charges on promotion, the
    QoS-aware policy protects Guarantee host bytes, and nothing
    recompiles after warmup (promotion is one warmed upload shape)."""

    # the demote-then-promote driver sequence: r0 seeds the cache, two
    # flushers (29 tokens -> 8 blocks each on a 12-block pool) drain it
    # through the tier, "hit" re-matches r0's prefix from host RAM
    def _tier_reqs(self, rng, shared):
        return [
            dict(rid="r0", prompt=shared, max_new_tokens=3),
            dict(rid="f1", prompt=rng.integers(0, 64, 29),
                 max_new_tokens=3),
            dict(rid="f2", prompt=rng.integers(0, 64, 29),
                 max_new_tokens=3),
            dict(rid="hit", prompt=np.concatenate(
                [shared, rng.integers(0, 64, 4)]), max_new_tokens=3),
        ]

    def _run_sequentially(self, engine, reqs):
        from kubeshare_tpu.serving import Request

        out = {}
        for req in reqs:
            engine.submit(Request(**req))
            out.update({rid: r.tokens for rid, r in engine.run().items()
                        if r.done})
            engine.pop_finished()
        return out

    def _tier_engine(self, params, config, registry=None, **over):
        from kubeshare_tpu.serving import EngineConfig, ServingEngine

        kwargs = dict(num_slots=1, block_size=4, num_blocks=13,
                      max_request_len=32, prefill_chunk=8,
                      host_tier_bytes=1 << 20)
        kwargs.update(over)
        return ServingEngine(params, config, EngineConfig(**kwargs),
                             tenants=registry)

    def test_wire_roundtrip_bit_identical(self):
        """The wire-format layer: pack -> unpack -> pack is the
        identity, bit for bit, and foreign bytes are rejected loudly —
        the contract a cross-slice shipper will inherit."""
        from kubeshare_tpu.serving import (KV_WIRE_VERSION, pack_block,
                                           unpack_block,
                                           wire_block_bytes)

        rng = np.random.default_rng(0)
        k = rng.standard_normal((2, 2, 4, 8)).astype(np.float32)
        v = rng.standard_normal((2, 2, 4, 8)).astype(np.float32)
        toks = np.asarray([5, 9, 2], np.int32)  # partial block (3 < 4)
        buf = pack_block(toks, k, v)
        assert len(buf) == wire_block_bytes(3, 2, 2, 4, 8, 4)
        t2, k2, v2 = unpack_block(buf)
        assert np.array_equal(t2, toks) and t2.dtype == np.int32
        assert np.array_equal(k2, k) and k2.dtype == k.dtype
        assert np.array_equal(v2, v)
        assert pack_block(t2, k2, v2) == buf  # the identity, re-packed
        assert KV_WIRE_VERSION == 2
        # bfloat16 — the model's flagship dtype — must round-trip too:
        # numpy's .str tag for it is an opaque void ('<V2'), so the
        # format carries the dtype NAME (review regression: promotion
        # crashed on jnp.asarray of a void-dtype slab)
        kb = k.astype(jnp.bfloat16)
        tb, kb2, vb2 = unpack_block(pack_block(toks, np.asarray(kb),
                                               np.asarray(kb)))
        assert kb2.dtype == np.asarray(kb).dtype
        assert np.array_equal(kb2.view(np.uint16),
                              np.asarray(kb).view(np.uint16))
        assert jnp.asarray(kb2).dtype == jnp.bfloat16  # promotion path
        # magic/version rejection requires an INTACT buffer: the v2 crc
        # is checked before any header field, so tampered headers must
        # be re-sealed to reach the magic/version checks at all
        import struct as _struct
        import zlib as _zlib

        def reseal(b: bytes) -> bytes:
            return b[:-4] + _struct.pack(
                "<I", _zlib.crc32(b[:-4]) & 0xFFFFFFFF)

        with pytest.raises(ValueError, match="magic"):
            unpack_block(reseal(b"XXXX" + buf[4:]))
        with pytest.raises(ValueError, match="version"):
            unpack_block(reseal(buf[:4] + b"\x63\x00" + buf[6:]))
        with pytest.raises(ValueError, match="truncated"):
            unpack_block(buf[:10])
        # v2 integrity: any single flipped byte — header, tokens, slab,
        # or the trailer itself — is a typed WireCorruption, loudly
        # distinct from honest foreign bytes
        from kubeshare_tpu.serving.kv_tier import _HEADER, WireCorruption
        for at in (0, 5, _HEADER.size + 1, len(buf) // 2, len(buf) - 1):
            bad = bytearray(buf)
            bad[at] ^= 0x40
            with pytest.raises(WireCorruption):
                unpack_block(bytes(bad))

    def test_demote_promote_roundtrip_is_byte_identical(self):
        """Device rows -> host payload -> device rows, bit for bit:
        capture a cached chain's K/V slabs, flush it through the tier,
        verify the host payloads equal the captured slabs, re-admit the
        prefix and verify the promoted blocks' device rows equal them
        too."""
        from kubeshare_tpu.serving import Request, unpack_block

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        engine = self._tier_engine(params, config)
        rng = np.random.default_rng(7)
        shared = rng.integers(0, 64, 13)
        engine.submit(Request("r0", shared, 3))
        engine.run()
        matched, blocks = engine.prefix_index.match(shared)
        assert matched == 13 and len(blocks) == 4  # 3 full + partial
        slabs = [(np.asarray(engine.pool.k[:, b]),
                  np.asarray(engine.pool.v[:, b])) for b in blocks[:3]]
        for rid in ("f1", "f2"):  # flush the cache through the tier
            engine.submit(Request(rid, rng.integers(0, 64, 29), 3))
            engine.run()
        assert engine.tier_demoted_blocks > 0
        matched, chain = engine.prefix_index.match_tiered(shared)
        assert matched == 13
        host_nodes = [n for n in chain[:3] if n.location == "host"]
        assert len(host_nodes) == 3  # the whole chain spilled
        for node, (k_slab, v_slab) in zip(chain[:3], slabs):
            _, hk, hv = unpack_block(
                engine.host_tier.peek(node.host_key).payload)
            assert np.array_equal(hk, k_slab)  # wire == device rows
            assert np.array_equal(hv, v_slab)
        engine.submit(Request("hit", shared.copy(), 3))
        engine.run()
        assert engine.tier_promoted_blocks >= 3
        matched, blocks = engine.prefix_index.match(shared)
        assert matched >= 12  # device-resident again
        for b, (k_slab, v_slab) in zip(blocks[:3], slabs):
            assert np.array_equal(np.asarray(engine.pool.k[:, b]), k_slab)
            assert np.array_equal(np.asarray(engine.pool.v[:, b]), v_slab)

    def test_streams_bit_exact_with_tier_across_configs(self):
        """Tier on vs tier off, token for token, through forced
        demote -> promote cycles — GQA, windowed, and MoE attention."""
        cases = {
            "gqa_rope": dict(n_kv_heads=2, positional="rope"),
            "windowed": dict(attention_window=6),
            "moe": dict(moe_every=2, moe_num_experts=4, moe_top_k=2),
        }
        rng = np.random.default_rng(11)
        shared = rng.integers(0, 64, 13)
        reqs = self._tier_reqs(rng, shared)
        for name, extra in cases.items():
            config = _small_config(**extra)
            params = transformer_init(jax.random.PRNGKey(0), config)
            tiered = self._tier_engine(params, config)
            plain = self._tier_engine(params, config,
                                      host_tier_bytes=None)
            got = self._run_sequentially(tiered, reqs)
            want = self._run_sequentially(plain, reqs)
            assert got == want, name
            assert tiered.tier_demoted_blocks > 0, name
            assert tiered.tier_promoted_blocks > 0, name
            assert tiered.tier_hit_requests > 0, name
            assert plain.tier_demoted_blocks == 0

    def test_sampled_streams_bit_exact_with_tier(self):
        """The key schedule survives a host-tier hit: sampled requests
        through demote/promote emit exactly the tier-off streams."""
        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        rng = np.random.default_rng(13)
        shared = rng.integers(0, 64, 13)
        reqs = []
        for i, req in enumerate(self._tier_reqs(rng, shared)):
            req.update(temperature=0.8, rng=jax.random.PRNGKey(40 + i))
            reqs.append(req)
        tiered = self._tier_engine(params, config, top_k=10)
        plain = self._tier_engine(params, config, top_k=10,
                                  host_tier_bytes=None)
        got = self._run_sequentially(tiered, reqs)
        want = self._run_sequentially(plain, reqs)
        assert got == want
        assert tiered.tier_promoted_blocks > 0

    def test_cow_divergence_on_promoted_block(self):
        """A prompt diverging mid-block INSIDE a promoted block takes
        the standard CoW path (the promoted block is shared state) and
        still emits its solo reference stream."""
        from kubeshare_tpu.models.decoding import greedy_decode

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        engine = self._tier_engine(params, config)
        rng = np.random.default_rng(17)
        shared = rng.integers(0, 64, 13)
        diverge = np.concatenate([shared, rng.integers(0, 64, 4)])
        diverge[9] = (diverge[9] + 1) % 64  # inside the 3rd block
        reqs = self._tier_reqs(rng, shared) + [
            dict(rid="cow", prompt=diverge, max_new_tokens=4)]
        got = self._run_sequentially(engine, reqs)
        assert engine.tier_promoted_blocks >= 3   # "hit" promoted
        assert engine.cow_copies >= 1             # "cow" diverged on it
        ref = np.asarray(greedy_decode(
            params, config, jnp.asarray(diverge, jnp.int32)[None], 4))[0]
        assert got["cow"] == list(ref)

    def test_qos_policy_protects_guarantee_host_bytes(self):
        """The tenant-aware policy's asymmetry, at the store level:
        Guarantee pressure evicts Opportunistic entries first (even
        when a Guarantee entry is colder), and Opportunistic pressure
        that could only fit by evicting Guarantee bytes is REFUSED —
        the incoming block drops instead."""
        from kubeshare_tpu.serving import (QOS_OPPORTUNISTIC, HostTier,
                                           QoSTierPolicy, TenantRegistry,
                                           TenantSpec)

        registry = TenantRegistry([
            TenantSpec("gold"),
            TenantSpec("batch", qos_class=QOS_OPPORTUNISTIC)])
        tier = HostTier(3 * 100, QoSTierPolicy(registry))
        pay = b"x" * 100
        g_old = tier.put(pay, "gold", None)   # coldest entry
        b_mid = tier.put(pay, "batch", None)
        g_new = tier.put(pay, "gold", None)
        assert len(tier) == 3  # budget exactly full
        # Guarantee incoming: the batch entry goes, NOT the colder gold
        g_more = tier.put(pay, "gold", None)
        assert g_more is not None
        keys = {e.key for _, e in tier.iter_lru()}
        assert b_mid not in keys and g_old in keys and g_new in keys
        assert tier.evicted_blocks == 1
        # Opportunistic incoming vs an all-Guarantee store: refused
        assert tier.put(pay, "batch", None) is None
        assert tier.refused_blocks == 1
        assert len(tier) == 3 and g_more in {
            e.key for _, e in tier.iter_lru()}

    def test_guarantee_demotion_evicts_opportunistic_host_blocks(self):
        """Engine-level class asymmetry: with the qos tier policy and a
        host budget already holding Guarantee entries, an Opportunistic
        tenant's spills are dropped (the Guarantee prefix survives) and
        the Guarantee tenant's later re-admission promotes from host."""
        from kubeshare_tpu.models.decoding import greedy_decode
        from kubeshare_tpu.serving import (QOS_OPPORTUNISTIC, Request,
                                           TenantRegistry, TenantSpec,
                                           wire_block_bytes)

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        registry = TenantRegistry([
            TenantSpec("gold"),
            TenantSpec("batch", qos_class=QOS_OPPORTUNISTIC)])
        full_wire = wire_block_bytes(4, config.n_layers, config.kv_heads,
                                     4, config.head_dim, 4)
        engine = self._tier_engine(
            params, config, registry=registry, tier_policy="qos",
            host_tier_bytes=4 * full_wire + 200)
        rng = np.random.default_rng(23)
        shared = rng.integers(0, 64, 13)
        engine.submit(Request("g0", shared, 3, tenant="gold"))
        engine.run()
        # batch flushers: gold's chain demotes (charged to gold), then
        # batch's own spills must NOT evict it — they drop
        for i, rid in enumerate(("b1", "b2")):
            engine.submit(Request(rid, rng.integers(0, 64, 29), 3,
                                  tenant="batch"))
            engine.run()
        assert engine.tier_demoted_blocks > 0
        assert engine.tier_dropped_blocks > 0  # batch spills refused
        tenants_left = {e.tenant for _, e in engine.host_tier.iter_lru()}
        assert tenants_left == {"gold"}  # Guarantee bytes survived
        hit = np.concatenate([shared, rng.integers(0, 64, 4)])
        engine.submit(Request("ghit", hit, 3, tenant="gold"))
        out = engine.run()
        assert engine.tier_promoted_blocks > 0
        ref = np.asarray(greedy_decode(
            params, config, jnp.asarray(hit, jnp.int32)[None], 3))[0]
        assert out["ghit"].tokens == list(ref)

    def test_demotion_uncharges_quota_promotion_recharges(self):
        """The quota-honesty satellite, regression-locked: a tenant
        whose idle cache was DEMOTED stops being charged for it (a
        quota-sized request then admits), and promotion re-charges the
        blocks through the normal reservation."""
        from kubeshare_tpu.models.decoding import greedy_decode
        from kubeshare_tpu.serving import Request, TenantRegistry, TenantSpec

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        registry = TenantRegistry([
            TenantSpec("t", kv_block_quota=6), TenantSpec("u")])
        engine = self._tier_engine(params, config, registry=registry)
        rng = np.random.default_rng(29)
        shared = rng.integers(0, 64, 13)
        engine.submit(Request("a", shared, 3, tenant="t"))
        engine.run()
        assert engine.allocator.tenant_usage("t") == 4  # idle, charged
        for rid in ("u1", "u2"):  # u's traffic demotes t's cache
            engine.submit(Request(rid, rng.integers(0, 64, 29), 3,
                                  tenant="u"))
            engine.run()
        assert engine.tier_demoted_blocks > 0
        assert engine.allocator.tenant_usage("t") == 0  # uncharged
        # quota-sized request admits cleanly (17 + 7 = 24 rows = 6
        # blocks = the whole quota — impossible if the demoted cache
        # still occupied the ledger)
        p_big = rng.integers(0, 64, 17)
        engine.submit(Request("b", p_big, 7, tenant="t"))
        out = engine.run()
        ref = np.asarray(greedy_decode(
            params, config, jnp.asarray(p_big, jnp.int32)[None], 7))[0]
        assert out["b"].tokens == list(ref)
        # promotion re-charges: t's host-resident prefix comes back as
        # a normal charged reservation
        engine.submit(Request("a2", np.concatenate(
            [shared, rng.integers(0, 64, 4)]), 3, tenant="t"))
        out = engine.run()
        assert engine.tier_promoted_blocks > 0
        assert engine.allocator.tenant_usage("t") >= 3
        assert engine.allocator.tenant_usage("t") <= 6  # quota held

    def test_eviction_reason_metrics(self):
        """The eviction family's `reason` label: reservation pressure
        and quota drain when tiering is off, tier_demote / tier_drop
        when the tier is consulted — all four series always present."""
        from kubeshare_tpu.serving import Request, TenantRegistry, TenantSpec

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        rng = np.random.default_rng(31)
        # tiering OFF: a quota own-drain, then reservation pressure
        registry = TenantRegistry([
            TenantSpec("t", kv_block_quota=6), TenantSpec("u")])
        plain = self._tier_engine(params, config, registry=registry,
                                  host_tier_bytes=None)
        plain.submit(Request("a", rng.integers(0, 64, 13), 3, tenant="t"))
        plain.run()
        plain.submit(Request("b", rng.integers(0, 64, 17), 7, tenant="t"))
        plain.run()  # 4 cached + 6 needed > 6 -> own-cache quota drain
        assert plain.evictions_by_reason["quota_drain"] > 0
        plain.submit(Request("c", rng.integers(0, 64, 29), 3, tenant="u"))
        plain.run()
        assert plain.evictions_by_reason["reservation_pressure"] > 0
        assert plain.evictions_by_reason["tier_demote"] == 0
        families = {f.name: f for f in plain.collect_metrics()}
        fam = families["kubeshare_serving_prefix_evicted_blocks_total"]
        reasons = {s.labels["reason"] for s in fam.samples}
        assert reasons == {"reservation_pressure", "quota_drain",
                           "tier_demote", "tier_drop"}
        total = sum(s.value for s in fam.samples)
        assert total == plain.allocator.evicted_blocks
        # tiering ON: the same pressure reads tier_demote (and
        # tier_drop once the host budget refuses)
        tiered = self._tier_engine(params, config)
        shared = rng.integers(0, 64, 13)
        for req in self._tier_reqs(rng, shared):
            tiered.submit(Request(**req))
            tiered.run()
        assert tiered.evictions_by_reason["tier_demote"] > 0
        assert tiered.evictions_by_reason["reservation_pressure"] == 0

    def test_host_budget_lru_eviction_and_pinning(self):
        """The store's budget discipline: LRU eviction keeps
        used_bytes under budget, pinned entries are never victims, and
        an all-pinned store refuses the incoming block."""
        from kubeshare_tpu.serving import HostTier, LRUTierPolicy

        tier = HostTier(2 * 100, LRUTierPolicy())
        pay = b"x" * 100
        k1 = tier.put(pay, None, None)
        k2 = tier.put(pay, None, None)
        k3 = tier.put(pay, None, None)  # evicts k1 (coldest)
        keys = {e.key for _, e in tier.iter_lru()}
        assert keys == {k2, k3} and tier.used_bytes == 200
        assert tier.evicted_blocks == 1
        tier.pin(k2)
        k4 = tier.put(pay, None, None)  # k2 pinned -> k3 goes
        assert {e.key for _, e in tier.iter_lru()} == {k2, k4}
        tier.pin(k4)
        assert tier.put(pay, None, None) is None  # all pinned: refused
        assert tier.refused_blocks == 1
        tier.unpin(k2)
        assert tier.put(pay, None, None) is not None
        # oversized payloads can never fit and are refused up front
        assert tier.put(b"y" * 300, None, None) is None

    def test_subtree_demotion_survives_one_block_host_budget(self):
        """Review regression: demoting a multi-block subtree under a
        host budget too small for all of it must NOT let the tier evict
        the just-demoted ancestor to fund its own descendants — the
        ancestor transiently has device-resident children mid-walk, and
        detaching it then corrupted trie/allocator state (RuntimeError
        under the allocator lock).  Walk-local pinning makes the
        descendants DROP instead, and every device block still comes
        back to the free list."""
        from kubeshare_tpu.serving import Request, wire_block_bytes

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        full_wire = wire_block_bytes(4, config.n_layers, config.kv_heads,
                                     4, config.head_dim, 4)
        engine = self._tier_engine(params, config,
                                   host_tier_bytes=full_wire)
        rng = np.random.default_rng(41)
        shared = rng.integers(0, 64, 13)
        engine.submit(Request("r0", shared, 3))
        engine.run()
        # evict the CHAIN HEAD directly — the victim shape reserve's
        # preferred-tenant scan produces for a mixed-charge chain (its
        # head can be the first idle block charged to the preferred
        # victim tenant, taking the whole subtree parent-first)
        matched, blocks = engine.prefix_index.match(shared)
        assert matched == 13
        with engine.allocator._lock:
            engine.allocator._evict_locked(blocks[0],
                                           "reservation_pressure")
        # head demoted (pinned through the walk), descendants dropped
        # when the one-entry budget could not take them; nothing raised
        assert engine.tier_demoted_blocks == 1
        assert engine.tier_dropped_blocks == 3
        assert len(engine.host_tier) == 1
        survivor = next(e.key for _, e in engine.host_tier.iter_lru())
        assert not engine.host_tier.is_pinned(survivor)  # pin released
        # allocator conservation: every block is free or idle-cached
        assert (engine.allocator.free_blocks
                + engine.allocator.cached_idle_blocks
                == engine.allocator.num_blocks - 1)

    def test_zero_recompiles_with_tier_promotions(self):
        """Acceptance criterion: warmup covers the upload shape, so a
        workload full of demotions and promotions adds ZERO compiled
        shapes beyond the warmed set."""
        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        engine = self._tier_engine(params, config)
        engine.warmup()
        baseline = engine.compile_counts()
        assert baseline["upload"] == 1  # the tier's single extra shape
        rng = np.random.default_rng(37)
        shared = rng.integers(0, 64, 13)
        self._run_sequentially(engine, self._tier_reqs(rng, shared))
        assert engine.tier_demoted_blocks > 0
        assert engine.tier_promoted_blocks > 0
        assert engine.compile_counts() == baseline


class TestDrafter:
    """serving/drafter.py edge cases: the n-gram lookup's contract is
    deliberately small (correctness never depends on it — only the
    acceptance rate does) but its determinism is what the bit-exactness
    tests lean on."""

    def test_empty_history_proposes_nothing(self):
        from kubeshare_tpu.serving import NGramDrafter

        d = NGramDrafter(3)
        assert d.propose(4) == []
        assert d.history == []

    def test_prompt_shorter_than_order_degrades_to_lower_orders(self):
        from kubeshare_tpu.serving import NGramDrafter

        # 2 tokens < order 3: only order 1 has an earlier occurrence
        d = NGramDrafter(3, [7, 7])
        assert d.propose(4) == [7]
        # a single token has NO earlier occurrence at any order
        assert NGramDrafter(3, [7]).propose(4) == []

    def test_most_recent_occurrence_wins(self):
        from kubeshare_tpu.serving import NGramDrafter

        # suffix [1, 2] occurs at i=0 (followed by 9) and i=4
        # (followed by 8): recency wins
        d = NGramDrafter(3, [1, 2, 9, 3, 1, 2, 8, 1, 2])
        assert d.propose(1) == [8]
        assert d.propose(3) == [8, 1, 2]

    def test_longest_suffix_beats_recent_shorter_match(self):
        from kubeshare_tpu.serving import NGramDrafter

        # order-3 suffix [5, 6, 7] matches only at i=0 (follower 9);
        # the order-1 suffix [7] ALSO matches more recently (follower
        # 3) — the longer suffix must win
        d = NGramDrafter(3, [5, 6, 7, 9, 2, 7, 3, 5, 6, 7])
        assert d.propose(1) == [9]

    def test_hint_window_used_only_on_history_miss(self):
        from kubeshare_tpu.serving import NGramDrafter

        d = NGramDrafter(2, [1, 2, 3])
        assert d.propose(2) == []          # no earlier occurrence
        d.hint([1, 2, 3, 4, 5])            # the trie's continuation
        assert d.propose(2) == [4, 5]
        # once the lane's OWN history matches, it wins over the hint
        d.extend([9, 2, 3])
        assert d.propose(1) == [9]

    def test_propose_bounds_and_validation(self):
        from kubeshare_tpu.serving import NGramDrafter

        d = NGramDrafter(1, [3, 5, 3, 5, 3])
        assert d.propose(0) == []
        assert d.propose(2) == [5, 3]      # k caps the draft
        assert d.propose(9) == [5, 3]      # ...and the window ends it
        # a match whose followers run out mid-draft yields what exists:
        # the most recent [4, 4] occurrence has ONE follower
        assert NGramDrafter(2, [4, 4, 4, 4]).propose(2) == [4]
        with pytest.raises(ValueError, match="max_order"):
            NGramDrafter(0)

    def test_engine_truncates_draft_at_remaining_budget(self):
        """A verify round emits at most k + 1 tokens, so the engine
        must cap every draft at remaining - 1: a 3-token budget on a
        loud repeating prompt (draft_len 8) may never dispatch a
        proposal wider than 2 — and the stream still ends exactly at
        max_new_tokens, matching the non-speculative run."""
        from kubeshare_tpu.models.decoding import greedy_decode
        from kubeshare_tpu.serving import Request

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        rng = np.random.default_rng(51)
        p0 = rng.integers(0, 64, 8)
        # extend the prompt with the model's OWN greedy continuation
        # (it settles into a loop): generation provably keeps looping,
        # so the drafter always has a matching suffix to propose from
        cont = np.asarray(greedy_decode(
            params, config, jnp.asarray(p0, jnp.int32)[None], 13))[0]
        prompt = np.concatenate([p0, cont]).astype(np.int32)
        streams = {}
        for spec in (True, False):
            engine = _engine(params, config, speculative=spec,
                             draft_len=8)
            seen_ks = []
            if spec:
                orig = engine._verify_step

                def recording(w, pk, pv, tables, lengths, active,
                              tokens, widths, temps, keys):
                    seen_ks.append(int(np.asarray(widths).max()) - 1)
                    return orig(w, pk, pv, tables, lengths, active,
                                tokens, widths, temps, keys)

                engine._verify_step = recording
            engine.submit(Request("r0", prompt, 3))
            streams[spec] = engine.run()["r0"].tokens
            if spec:
                assert seen_ks, "speculation never engaged"
                assert max(seen_ks) <= 2  # rem - 1 with 3 to go
        assert streams[True] == streams[False]
        assert len(streams[True]) == 3


class TestSpeculative:
    """Tentpole contract: self-drafting speculative decoding emits
    EXACTLY the streams sequential decoding emits — by construction
    (exact-match verification against the target's own picks), across
    attention variants, greedy and sampled, mixed batching on and off,
    and across preemption-resume — while spending fewer target
    dispatches per token on repetitive traffic, with zero compiled
    shapes added after warmup."""

    def _streams(self, engine, reqs):
        from kubeshare_tpu.serving import Request

        for req in reqs:
            engine.submit(Request(**req))
        return {rid: r.tokens for rid, r in engine.run().items()}

    def _workload(self, rng, sampled=False):
        base = rng.integers(0, 64, 6)
        reqs = [
            # repetitive prompts: the traffic speculation exists for
            dict(rid="rep0", prompt=np.tile(base, 4)[:22],
                 max_new_tokens=10),
            dict(rid="rep1", prompt=np.tile(rng.integers(0, 64, 4),
                                            5)[:17], max_new_tokens=8),
            # incompressible control lane rides verify at width 1
            dict(rid="rand", prompt=rng.integers(0, 64, 9),
                 max_new_tokens=6),
        ]
        if sampled:
            reqs.append(dict(rid="samp", prompt=np.tile(base, 3)[:15],
                             max_new_tokens=9, temperature=0.8,
                             rng=jax.random.PRNGKey(43)))
        return reqs

    def test_streams_bit_exact_spec_on_vs_off_across_configs(self):
        """Speculation on vs off, token for token, same workload —
        GQA+RoPE (with sampled lanes: the key schedule must be
        consumed identically through verify chunks), windowed
        attention, and MoE."""
        cases = {
            "gqa_rope": dict(n_kv_heads=2, positional="rope"),
            "windowed": dict(attention_window=6),
            "moe": dict(moe_every=2, moe_num_experts=4, moe_top_k=2),
        }
        accepted_total = 0
        for name, extra in cases.items():
            config = _small_config(**extra)
            params = transformer_init(jax.random.PRNGKey(0), config)
            rng = np.random.default_rng(52)
            sampled = name == "gqa_rope"
            workload = self._workload(rng, sampled=sampled)
            kwargs = dict(top_k=10, top_p=0.95) if sampled else {}
            on = _engine(params, config, speculative=True, draft_len=4,
                         **kwargs)
            off = _engine(params, config, **kwargs)
            got = self._streams(on, workload)
            want = self._streams(off, workload)
            assert got == want, name
            # speculation actually engaged (and the control arm's
            # sequential scheduler never verified)
            assert on.verify_steps > 0, name
            assert sum(on.spec_drafted.values()) > 0, name
            accepted_total += sum(on.spec_accepted.values())
            assert off.verify_steps == 0, name
        # whether a random-weight model's picks ever agree with the
        # lookup is per-config luck; across three configs some drafts
        # must land (acceptance QUALITY is locked in
        # test_fewer_dispatches_on_repetitive_trace and the bench)
        assert accepted_total > 0

    def test_streams_bit_exact_with_mixed_off(self):
        """Speculation composes with the either/or scheduler too —
        verify chunks replace decode spans identically when prefill
        never fuses."""
        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        rng = np.random.default_rng(53)
        workload = self._workload(rng)
        on = _engine(params, config, speculative=True, draft_len=4,
                     mixed=False)
        off = _engine(params, config, mixed=False)
        got = self._streams(on, workload)
        want = self._streams(off, workload)
        assert got == want
        assert on.verify_steps > 0
        assert on.mixed_verify_steps == 0 == on.mixed_steps

    def test_dense_and_paged_speculative_parity(self):
        """Satellite: the dense two-model speculative path
        (models/decoding.py) self-drafting and the engine's
        prompt-lookup path share one acceptance rule
        (speculative_acceptance) — self-drafted dense, engine
        speculative, and the plain greedy oracle all emit the SAME
        stream."""
        from kubeshare_tpu.models.decoding import (greedy_decode,
                                                   speculative_greedy_decode)
        from kubeshare_tpu.serving import Request

        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        rng = np.random.default_rng(54)
        prompt = np.tile(rng.integers(0, 64, 5), 4)[:18]
        oracle = np.asarray(greedy_decode(
            params, config, jnp.asarray(prompt)[None], 8))[0]
        dense = np.asarray(speculative_greedy_decode(
            params, config, params, config,
            jnp.asarray(prompt)[None], 8, draft_len=4))[0]
        engine = _engine(params, config, speculative=True, draft_len=4)
        engine.submit(Request("r0", prompt, 8))
        paged = engine.run()["r0"].tokens
        assert list(oracle) == list(dense) == paged

    def test_zero_recompiles_after_warmup(self):
        """Acceptance criterion: warmup covers every verify width the
        adaptive controller can reach (and the fused mixed-verify
        cross product) — a speculative workload with admissions,
        prefill fusion, drafting lanes and width adaptation compiles
        NOTHING new."""
        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        engine = _engine(params, config, speculative=True, draft_len=4)
        engine.warmup()
        baseline = engine.compile_counts()
        assert baseline["verify"] > 0
        assert baseline["mixed_verify"] > 0
        rng = np.random.default_rng(55)
        self._streams(engine, self._workload(rng, sampled=True))
        assert engine.verify_steps > 0
        assert engine.compile_counts() == baseline

    def test_fewer_dispatches_on_repetitive_trace(self):
        """The perf shape (the full criterion lives in the bench):
        on a loud repeating prompt the verify path spends measurably
        fewer target dispatches per emitted token than sequential
        decoding at decode_span=1 — same stream."""
        from kubeshare_tpu.serving import Request

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        rng = np.random.default_rng(56)
        prompt = np.tile(rng.integers(0, 64, 4), 8)[:30]
        counts = {}
        streams = {}
        for spec in (True, False):
            engine = _engine(params, config, speculative=spec,
                             draft_len=8, decode_span=1)
            engine.submit(Request("r0", prompt, 14))
            streams[spec] = engine.run()["r0"].tokens
            counts[spec] = engine.decode_steps + engine.verify_steps
        assert streams[True] == streams[False]
        assert counts[True] < counts[False]

    def test_preemption_resume_bit_exact_with_speculation(self):
        """Acceptance criterion: cache-backed preemption under a
        speculative engine — the victim's drafter is rebuilt from
        prompt + generated on resume and every stream still matches
        the greedy oracle.  The drafter-window invariant
        (history == prompt + generated, the resume-rebuild contract)
        is asserted on every decode lane at every step."""
        from kubeshare_tpu.models.decoding import greedy_decode
        from kubeshare_tpu.serving import (QOS_OPPORTUNISTIC, EngineConfig,
                                           Request, ServingEngine,
                                           TenantRegistry, TenantSpec)

        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        registry = TenantRegistry([
            TenantSpec("gold"),
            TenantSpec("batch", qos_class=QOS_OPPORTUNISTIC),
        ])
        engine = ServingEngine(params, config, EngineConfig(
            num_slots=3, block_size=4, num_blocks=13,
            max_request_len=32, prefill_chunk=8, speculative=True,
            draft_len=4), tenants=registry)
        rng = np.random.default_rng(57)
        # repetitive victims: the resumed lane must KEEP drafting from
        # its rebuilt window (pre-preemption emissions included)
        p0 = np.tile(rng.integers(0, 64, 5), 1)
        p1 = rng.integers(0, 64, 5)
        pg = rng.integers(0, 64, 10)

        def check_drafter_invariant():
            for s in engine._slots:
                if s.state == "decode" and s.drafter is not None:
                    assert s.drafter.history == \
                        list(s.prompt) + list(s.generated), s.rid

        engine.submit(Request("v0", p0, 19, tenant="batch"))
        engine.submit(Request("v1", p1, 19, tenant="batch"))

        def both_decoding():
            slots = [s for s in engine._slots
                     if s.rid in ("v0", "v1")]
            return len(slots) == 2 and all(
                s.state == "decode" and len(s.generated) >= 2
                for s in slots)

        while not both_decoding():
            assert engine.step()
            check_drafter_invariant()
        engine.submit(Request("gold", pg, 4, tenant="gold"))
        results = {}
        while engine.step():
            check_drafter_invariant()
            for rid, res in list(engine._results.items()):
                if res.finished_at is not None:
                    results[rid] = res
        assert engine.preemptions.get("batch", 0) >= 1
        for rid, prompt, new in (("v0", p0, 19), ("v1", p1, 19),
                                 ("gold", pg, 4)):
            ref = np.asarray(greedy_decode(
                params, config, jnp.asarray(prompt, jnp.int32)[None],
                new))[0]
            assert results[rid].tokens == list(ref), rid
        assert engine.allocator.blocks_in_use == 0

    def test_spec_metrics_on_plane(self):
        """Satellite: drafted/accepted counters and the per-tenant
        acceptance-rate histogram ride the promtext scrape surface and
        reconcile with the engine's own counters."""
        from kubeshare_tpu.serving import Request
        from kubeshare_tpu.utils.promtext import encode_families, parse_text

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        engine = _engine(params, config, speculative=True, draft_len=4)
        rng = np.random.default_rng(58)
        prompt = np.tile(rng.integers(0, 64, 4), 6)[:22]
        engine.submit(Request("r0", prompt, 10))
        engine.run()
        assert engine.verify_steps > 0
        samples = {(s.name, tuple(sorted(s.labels.items()))): s.value
                   for s in parse_text(
                       encode_families(engine.collect_metrics()))}
        drafted = engine.spec_drafted.get("default", 0)
        accepted = engine.spec_accepted.get("default", 0)
        assert drafted > 0 and 0 < accepted <= drafted
        assert samples[("kubeshare_serving_spec_tokens_total",
                        (("kind", "drafted"),
                         ("tenant", "default")))] == drafted
        assert samples[("kubeshare_serving_spec_tokens_total",
                        (("kind", "accepted"),
                         ("tenant", "default")))] == accepted
        # one histogram observation per drafting verify round
        rounds = samples[("kubeshare_serving_spec_acceptance_ratio_count",
                          (("tenant", "default"),))]
        assert 0 < rounds <= engine.verify_steps
        # the +Inf bucket is cumulative: every round lands in it
        assert samples[("kubeshare_serving_spec_acceptance_ratio_bucket",
                        (("le", "+Inf"),
                         ("tenant", "default")))] == rounds
        kinds = {k[1][0][1]: v for k, v in samples.items()
                 if k[0] == "kubeshare_serving_dispatches_total"}
        assert kinds["verify_span"] + kinds["mixed_verify"] == \
            engine.verify_steps


class TestDisagg:
    """Tentpole contract: the split-pool disaggregated engine (prefill
    pool + decode pool + KV-chain migration over the tier wire format)
    emits EXACTLY the monolithic engine's streams — greedy and sampled,
    across GQA/windowed/MoE, speculation on or off, across preemption —
    with zero recompiles after both pools warm up."""

    MONO = dict(num_slots=3, block_size=4, num_blocks=41,
                max_request_len=48, prefill_chunk=8, mixed=False)
    PREFILL = dict(num_slots=2, block_size=4, num_blocks=17,
                   max_request_len=48, prefill_chunk=8, mixed=False)
    DECODE = dict(num_slots=3, block_size=4, num_blocks=25,
                  max_request_len=48, prefill_chunk=8, mixed=False)

    def _mono(self, params, config, tenants=None, **overrides):
        from kubeshare_tpu.serving import EngineConfig, ServingEngine

        kwargs = dict(self.MONO)
        kwargs.update(overrides)
        return ServingEngine(params, config, EngineConfig(**kwargs),
                             tenants=tenants)

    def _router(self, params, config, prefill=None, decode=None,
                shared=None, **kwargs):
        from kubeshare_tpu.serving import DisaggRouter, EngineConfig

        p = dict(self.PREFILL)
        p.update(prefill or {})
        p.update(shared or {})
        d = dict(self.DECODE)
        d.update(decode or {})
        d.update(shared or {})
        return DisaggRouter(params, config, EngineConfig(**p),
                            EngineConfig(**d), **kwargs)

    def _streams(self, engine, reqs):
        from kubeshare_tpu.serving import Request

        for req in reqs:
            engine.submit(Request(**req))
        return {rid: r.tokens for rid, r in engine.run().items()}

    def test_streams_bit_exact_disagg_vs_monolithic_across_configs(self):
        """Disagg vs monolithic, token for token: the migrated slot is
        indistinguishable from one that finished prefill in place.
        Prompt lengths deliberately off block-size multiples, so every
        chain ships a sub-block partial tail frame; the GQA case adds
        SAMPLED lanes (the per-request key schedule must survive the
        handoff: emission k decode-side consumes exactly the key the
        monolithic engine's emission k would)."""
        cases = {
            "gqa_rope": dict(n_kv_heads=2, positional="rope"),
            "windowed": dict(attention_window=6),
            "moe": dict(moe_every=2, moe_num_experts=4, moe_top_k=2),
        }
        rng = np.random.default_rng(61)
        reqs = [
            dict(rid="long", prompt=rng.integers(0, 64, 29),
                 max_new_tokens=6),
            dict(rid="s0", prompt=rng.integers(0, 64, 5),
                 max_new_tokens=8),
            dict(rid="s1", prompt=rng.integers(0, 64, 13),
                 max_new_tokens=4),
        ]
        sampled = [
            dict(rid="samp", prompt=rng.integers(0, 64, 11),
                 max_new_tokens=7, temperature=0.8,
                 rng=jax.random.PRNGKey(62)),
            dict(rid="samp2", prompt=rng.integers(0, 64, 21),
                 max_new_tokens=5, temperature=1.1,
                 rng=jax.random.PRNGKey(63)),
        ]
        for name, extra in cases.items():
            config = _small_config(**extra)
            params = transformer_init(jax.random.PRNGKey(0), config)
            workload = reqs + (sampled if name == "gqa_rope" else [])
            shared = (dict(top_k=10, top_p=0.95)
                      if name == "gqa_rope" else {})
            mono = self._mono(params, config, **shared)
            router = self._router(params, config, shared=shared)
            mono.warmup()
            router.warmup()
            base = router.compile_counts()
            want = self._streams(mono, workload)
            got = self._streams(router, workload)
            assert got == want, name
            # every request crossed the wire exactly once...
            assert router.migrator.migrations == len(workload), name
            assert router.migrator.delivered == len(workload), name
            assert router.migrator.migrated_bytes > 0, name
            # ...each pool ran ONLY its phase's dispatches...
            assert router.prefill.decode_steps == 0, name
            assert router.decode.prefill_chunks == 0, name
            # ...and nothing recompiled after warmup
            assert router.compile_counts() == base, name

    def test_chain_wire_roundtrip_bfloat16_partial_tail(self):
        """The migration envelope: length-prefixed pack_block frames
        inside a pack_chain header, bfloat16 slabs, last frame a
        sub-block partial (stale tail rows ride along) — byte-identical
        round-trip, loud on foreign magic / version / zero frames."""
        from kubeshare_tpu.serving import (KV_CHAIN_VERSION, pack_block,
                                           pack_chain, unpack_block,
                                           unpack_chain)

        dtype = np.dtype(jnp.bfloat16.dtype)
        rng = np.random.default_rng(7)
        runs = [[1, 2, 3, 4], [5, 6, 7, 8], [9, 10]]  # partial tail
        slabs = [
            (rng.standard_normal((2, 2, 4, 8)).astype(dtype),
             rng.standard_normal((2, 2, 4, 8)).astype(dtype))
            for _ in runs]
        frames = [pack_block(toks, k, v)
                  for toks, (k, v) in zip(runs, slabs)]
        buf = pack_chain(frames)
        assert buf[:4] == b"KVCH"
        back = unpack_chain(buf)
        assert back == frames
        for toks, (k, v), frame in zip(runs, slabs, back):
            t2, k2, v2 = unpack_block(frame)
            assert list(t2) == toks
            assert k2.dtype == dtype and v2.dtype == dtype
            assert k2.tobytes() == k.tobytes()
            assert v2.tobytes() == v.tobytes()
        # loud failures: bad magic, bad version, empty chain
        with pytest.raises(ValueError, match="chain magic"):
            unpack_chain(b"XXCH" + buf[4:])
        bad = bytearray(buf)
        bad[4] = KV_CHAIN_VERSION + 1
        with pytest.raises(ValueError, match="chain version"):
            unpack_chain(bytes(bad))
        with pytest.raises(ValueError, match="at least one"):
            pack_chain([])

    def test_speculative_drafter_state_survives_handoff(self):
        """Spec-on disagg: the drafter's trie-continuation hint is
        captured at prefill admission, rides the ticket, and is
        reinstalled decode-side — so a cache-hit lane drafts (and
        accepts) after migration, and the stream still matches the
        monolithic spec engine token for token."""
        from kubeshare_tpu.serving import Request

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        phrase = [7, 11, 19, 7, 11, 19, 7, 11, 19, 7, 11, 19]
        full = np.asarray(phrase + [23, 29, 23, 29], np.int32)
        head = np.asarray(phrase[:8], np.int32)  # prefix of `full`

        def drive(eng):
            eng.submit(Request("warm", full, 4))
            eng.run()
            eng.submit(Request("b", head, 8))
            return eng.run()["b"].tokens

        mono = self._mono(params, config, speculative=True)
        mono.warmup()
        want = drive(mono)

        router = self._router(params, config,
                              shared=dict(speculative=True))
        router.warmup()
        base = router.compile_counts()
        tickets = []
        orig = router.migrator.pack

        def spy(engine, slot):
            ticket = orig(engine, slot)
            tickets.append(ticket)
            return ticket

        router.migrator.pack = spy
        got = drive(router)
        assert got == want
        assert router.compile_counts() == base
        # the cache-hit lane's ticket carried prompt + continuation
        assert tickets[1].hint is not None
        assert tickets[1].hint[:len(head)] == list(head)
        assert len(tickets[1].hint) > len(head)
        # and the rebuilt drafter actually drafted/accepted post-handoff
        assert sum(router.decode.spec_drafted.values()) >= 1
        assert sum(router.decode.spec_accepted.values()) >= 1

    def test_preemption_mid_migration_bit_exact(self):
        """A Guarantee ticket the decode pool cannot place preempts an
        Opportunistic decode slot; the victim's resume routes BACK
        through the prefill pool (re-prefill where prefill runs) and
        re-migrates — every stream still token-for-token identical to
        the monolithic engine, with zero recompiles."""
        from kubeshare_tpu.serving import (QOS_OPPORTUNISTIC, Request,
                                           TenantRegistry, TenantSpec)

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        tenants = TenantRegistry([
            TenantSpec("gold"),
            TenantSpec("batch", qos_class=QOS_OPPORTUNISTIC),
        ])
        rng = np.random.default_rng(5)
        v0p, v1p, gp = (rng.integers(0, 64, 8) for _ in range(3))

        def drive(eng, is_router):
            eng.submit(Request("v0", v0p, 24, tenant="batch"))
            eng.submit(Request("v1", v1p, 24, tenant="batch"))
            if is_router:  # both victims resident decode-side first
                while eng.migrator.delivered < 2:
                    eng.step()
            else:
                for _ in range(4):
                    eng.step()
            eng.submit(Request("g", gp, 6, tenant="gold",
                               temperature=0.9,
                               rng=jax.random.PRNGKey(77)))
            return {rid: r.tokens for rid, r in eng.run().items()}

        mono = self._mono(params, config, tenants=tenants)
        mono.warmup()
        want = drive(mono, False)

        # decode pool sized so the two victims fill it exactly
        router = self._router(params, config,
                              decode=dict(num_slots=2, num_blocks=17),
                              tenants=tenants)
        router.warmup()
        base = router.compile_counts()
        got = drive(router, True)
        assert got == want
        assert router.compile_counts() == base
        assert router.decode.preemptions.get("batch", 0) >= 1
        # the victim re-prefilled and re-migrated: 3 requests, 4 chains
        assert router.migrator.migrations >= 4
        assert router.migrator.delivered == router.migrator.migrations

    def test_shared_tier_is_cross_pool_cache_bus_and_meters_ledger(self):
        """One host tier under both tries: a chain the DECODE pool
        demoted (prompt + generated rows the prefill pool never held)
        is adopted into the PREFILL trie as host mirrors, and a later
        request extending that stream tier-promotes prefill-side.  The
        ledger hook sees every demote/promote/migrate byte — migrate
        bytes exactly matching the migrator's counter."""
        from kubeshare_tpu.serving import Request, ServingEngine

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        ledger = []
        router = self._router(
            params, config,
            decode=dict(num_slots=2, num_blocks=13),
            shared_tier_bytes=1 << 20,
            ledger_hook=lambda nbytes, kind: ledger.append((kind, nbytes)))
        router.warmup()
        base = router.compile_counts()
        rng = np.random.default_rng(9)
        pA = rng.integers(0, 64, 12)
        router.submit(Request("a0", pA, 6))
        a0 = router.run()["a0"].tokens
        # flood: drains the decode pool's cached chains into the shared
        # tier; the generated-row blocks mirror into the prefill trie
        for i in range(6):
            router.submit(Request(f"o{i}", rng.integers(0, 64, 12), 6))
        router.run()
        ext = np.concatenate([pA, np.asarray(a0, np.int32)])
        router.submit(Request("ext", ext, 4))
        got = router.run()["ext"].tokens
        assert router.compile_counts() == base
        # rows 12.. of `ext` exist ONLY via the decode pool's demoted
        # chain: serving them from the prefill pool proves the bus
        assert router.prefill.tier_hit_requests >= 1
        mono = self._mono(params, config)
        mono.warmup()
        mono.submit(Request("ext", ext, 4))
        assert got == mono.run()["ext"].tokens
        kinds = {}
        for kind, nbytes in ledger:
            assert nbytes > 0
            kinds[kind] = kinds.get(kind, 0) + nbytes
        assert set(kinds) == {"demote", "promote", "migrate"}
        assert kinds["migrate"] == router.migrator.migrated_bytes

    def test_migration_metrics_and_pool_labels(self):
        """The router's merged metrics plane: migration counters and
        the stall histogram are present, per-pool families carry the
        ``pool`` label both ways, and the monolithic engine's families
        stay UNLABELED (dashboards keyed on the old series survive)."""
        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        router = self._router(params, config)
        router.warmup()
        rng = np.random.default_rng(21)
        reqs = [dict(rid=f"r{i}", prompt=rng.integers(0, 64, 9),
                     max_new_tokens=4) for i in range(3)]
        self._streams(router, reqs)
        fams = {f.name: f for f in router.collect_metrics()}

        mig = fams["kubeshare_serving_migrations_total"]
        stages = {s.labels["stage"]: s.value for s in mig.samples}
        assert stages == {"packed": 3.0, "delivered": 3.0}
        assert fams["kubeshare_serving_migrated_bytes_total"] \
            .samples[0].value > 0
        stall = fams["kubeshare_serving_migration_stall_seconds"]
        counts = [s for s in stall.samples if s.name.endswith("_count")]
        assert counts and counts[0].value == 3.0

        disp = fams["kubeshare_serving_dispatches_total"]
        pools = {s.labels.get("pool") for s in disp.samples}
        assert pools == {"prefill", "decode"}
        ttft = fams["kubeshare_serving_ttft_seconds"]
        assert {"prefill", "decode"} <= {
            s.labels.get("pool") for s in ttft.samples}

        mono = self._mono(params, config)
        mono.warmup()
        self._streams(mono, reqs)
        mono_disp = {f.name: f for f in mono.collect_metrics()}[
            "kubeshare_serving_dispatches_total"]
        assert all("pool" not in s.labels for s in mono_disp.samples)

    def test_virtual_multislice_topology_places_pools_apart(self):
        """virtual_multislice topology: the pools land on devices from
        slice 0 and slice 1 of the dryrun 2-slice mesh (distinct CPU
        devices under conftest's 8-device virtual topology), the KV
        chain crosses that boundary, and streams stay bit-exact."""
        from kubeshare_tpu.constants import (ENV_MEGASCALE_NUM_SLICES,
                                             ENV_MEGASCALE_SLICE_ID)
        from kubeshare_tpu.parallel.distributed import \
            multislice_spec_from_env
        from kubeshare_tpu.serving import DisaggTopology

        if len(jax.devices()) < 2:
            pytest.skip("needs >= 2 devices")
        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        ms = multislice_spec_from_env({ENV_MEGASCALE_NUM_SLICES: "2",
                                       ENV_MEGASCALE_SLICE_ID: "0"})
        router = self._router(
            params, config,
            topology=DisaggTopology("virtual_multislice", ms))
        router.warmup()
        assert (router.prefill.pool.k.devices()
                != router.decode.pool.k.devices())
        rng = np.random.default_rng(51)
        reqs = [dict(rid="a", prompt=rng.integers(0, 64, 14),
                     max_new_tokens=5),
                dict(rid="b", prompt=rng.integers(0, 64, 7),
                     max_new_tokens=6)]
        mono = self._mono(params, config)
        mono.warmup()
        want = self._streams(mono, reqs)
        assert self._streams(router, reqs) == want
        assert router.migrator.delivered == 2

    def test_loud_misconfiguration(self):
        """The failure modes that must crash, not corrupt: geometry
        mismatch between pools, direct submit into a decode pool,
        mixed batching on a single-phase pool, and a request the decode
        pool could never hold (rejected BEFORE burning prefill work)."""
        from kubeshare_tpu.serving import (BlockExhausted, DecodePool,
                                           DisaggRouter, EngineConfig,
                                           Request, ServingEngine)

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        with pytest.raises(ValueError, match="disagree on block_size"):
            DisaggRouter(params, config,
                         EngineConfig(**self.PREFILL),
                         EngineConfig(**{**self.DECODE,
                                         "block_size": 8}))
        with pytest.raises(ValueError, match="mixed"):
            ServingEngine(params, config, EngineConfig(
                **{**self.PREFILL, "mixed": True,
                   "pool_role": "prefill"}))
        decode = DecodePool(params, config, EngineConfig(**self.DECODE))
        with pytest.raises(RuntimeError, match="admit_migrated"):
            decode.submit(Request("r", np.arange(4, dtype=np.int32), 2))
        router = self._router(params, config,
                              decode=dict(num_slots=2, num_blocks=5))
        with pytest.raises(BlockExhausted, match="NEVER migrate"):
            router.submit(Request("big", np.arange(20, dtype=np.int32),
                                  20))


class TestDeviceLoop:
    """Tentpole contract: ``steps_per_launch=K`` compiles ONE device-
    resident loop running up to K scheduler iterations of the paged
    decode span — sampling, stop/budget detection and the emitted-token
    ring all on device, early exit the moment any lane deactivates —
    and emits EXACTLY the K=1 streams, greedy and sampled, across
    GQA/windowed/MoE, preemption-resume and retire, with zero new
    compiled shapes after warmup."""

    def _pair(self, params, config, k, **overrides):
        from kubeshare_tpu.serving import EngineConfig, ServingEngine

        kwargs = dict(num_slots=3, block_size=4, num_blocks=41,
                      max_request_len=48, prefill_chunk=8,
                      steps_per_launch=k)
        kwargs.update(overrides)
        return ServingEngine(params, config, EngineConfig(**kwargs))

    def _streams(self, engine, reqs):
        from kubeshare_tpu.serving import Request

        for req in reqs:
            engine.submit(Request(**req))
        return {rid: r.tokens for rid, r in engine.run().items()}

    def test_streams_bit_exact_loop_on_vs_off_across_configs(self):
        """Loop on vs off, token for token, same workload: lanes at
        staggered budgets so launches exit early at different units,
        admissions landing between launches.  The GQA case carries
        SAMPLED lanes (the flat key index u*span+j must hand emission k
        exactly the key the K=1 re-marshaled dispatches would)."""
        cases = {
            "gqa_rope": dict(n_kv_heads=2, positional="rope"),
            "windowed": dict(attention_window=6),
            "moe": dict(moe_every=2, moe_num_experts=4, moe_top_k=2),
        }
        rng = np.random.default_rng(71)
        reqs = [
            dict(rid="long", prompt=rng.integers(0, 64, 29),
                 max_new_tokens=14),
            dict(rid="s0", prompt=rng.integers(0, 64, 5),
                 max_new_tokens=9),
            dict(rid="s1", prompt=rng.integers(0, 64, 13),
                 max_new_tokens=4),
            dict(rid="long2", prompt=rng.integers(0, 64, 21),
                 max_new_tokens=11),
        ]
        sampled = [
            dict(rid="samp", prompt=rng.integers(0, 64, 13),
                 max_new_tokens=12, temperature=0.8,
                 rng=jax.random.PRNGKey(72)),
            dict(rid="samp2", prompt=rng.integers(0, 64, 11),
                 max_new_tokens=7, temperature=1.1,
                 rng=jax.random.PRNGKey(73)),
        ]
        for name, extra in cases.items():
            config = _small_config(**extra)
            params = transformer_init(jax.random.PRNGKey(0), config)
            workload = reqs + (sampled if name == "gqa_rope" else [])
            kwargs = (dict(top_k=10, top_p=0.95)
                      if name == "gqa_rope" else {})
            on = self._pair(params, config, 4, **kwargs)
            off = self._pair(params, config, 1, **kwargs)
            got = self._streams(on, workload)
            want = self._streams(off, workload)
            assert got == want, name
            # the loop actually ran (and the control arm has none)
            assert on.loop_launches > 0, name
            assert on.loop_units > 0, name
            assert off.loop_launches == 0, name

    def test_planner_invocations_drop_on_decode_heavy_trace(self):
        """The point of the PR: on a decode-dominated trace the host
        planner runs ~K x fewer times per emitted token (each launch
        covers up to K iterations the K=1 engine plans one by one)."""
        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        rng = np.random.default_rng(74)
        reqs = [dict(rid="d", prompt=rng.integers(0, 64, 5),
                     max_new_tokens=32)]
        counts = {}
        for k in (1, 4):
            engine = self._pair(params, config, k)
            streams = self._streams(engine, list(reqs))
            assert len(streams["d"]) == 32
            counts[k] = engine.host_planner_invocations
            # the counter flows through the metrics plane
            sample = [sm for f in engine.collect_metrics()
                      if f.name ==
                      "kubeshare_serving_host_planner_invocations_total"
                      for sm in f.samples]
            assert sample and sample[0].value == counts[k]
        # 32 tokens / span 4 = 8 decode plans at K=1 vs 2 launches at
        # K=4; prefill + drain plans are common to both arms
        assert counts[4] < counts[1]
        assert counts[1] - counts[4] >= 4

    def test_mid_scan_preemption_resume_bit_exact(self):
        """A Guarantee admission preempting an Opportunistic lane MID
        FLIGHT under the loop: the in-flight ring is consumed first
        (its accepted tokens are real), the victim retires into the
        prefix cache and resumes emitting EXACTLY its unpreempted
        stream — against the dense greedy oracle."""
        from kubeshare_tpu.models.decoding import greedy_decode
        from kubeshare_tpu.serving import (QOS_OPPORTUNISTIC,
                                           EngineConfig, Request,
                                           ServingEngine,
                                           TenantRegistry, TenantSpec)

        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        registry = TenantRegistry([
            TenantSpec("gold"),
            TenantSpec("batch", qos_class=QOS_OPPORTUNISTIC),
        ])
        engine = ServingEngine(
            params, config,
            EngineConfig(num_slots=2, block_size=4, num_blocks=13,
                         max_request_len=32, prefill_chunk=8,
                         steps_per_launch=4),
            tenants=registry)
        engine.warmup()
        baseline = engine.compile_counts()
        rng = np.random.default_rng(75)
        # same block geometry as TestQoSPreemption (victim grows to 8
        # blocks, gold needs 6 > 4 free -> preempt) but the victim's
        # 22-token budget OUTLASTS one 16-deep launch (K*span), so gold
        # arrives while a launch is in flight: the preemption consumes
        # that ring first — its accepted tokens are real — then evicts
        p_batch = rng.integers(0, 64, 9)   # 9 + 22 = 31 rows, 8 blocks
        p_gold = rng.integers(0, 64, 18)   # 18 + 6 = 24 rows, 6 blocks
        engine.submit(Request("victim", p_batch, 22, tenant="batch"))
        while True:
            r = engine.result("victim")
            if r.first_token_at is not None and not r.done:
                break
            assert engine.step(), "engine idle before victim decoded"
        engine.submit(Request("gold", p_gold, 6, tenant="gold"))
        out = engine.run()
        assert engine.preemptions.get("batch", 0) >= 1
        assert engine.loop_launches >= 1
        for rid, prompt, new in (("victim", p_batch, 22),
                                 ("gold", p_gold, 6)):
            ref = np.asarray(greedy_decode(
                params, config, jnp.asarray(prompt, jnp.int32)[None],
                new))[0]
            assert out[rid].tokens == list(ref), rid
        assert engine.allocator.blocks_in_use == 0
        assert engine.compile_counts() == baseline

    def test_ring_drained_at_retire(self):
        """A budget ending mid-launch: the device detects it (budget
        check per emission, early exit at the unit boundary), the host
        drains the ring capped at the lane's budget — never a token
        past max_new_tokens, never a dropped one — and the launch
        stops short of its K units."""
        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        rng = np.random.default_rng(76)
        # 10 tokens, span 4, K=4: the sole lane dies at emission 10 of
        # a 16-deep ring -> exit after unit 3 of 4
        engine = self._pair(params, config, 4)
        streams = self._streams(
            engine, [dict(rid="short", prompt=rng.integers(0, 64, 5),
                          max_new_tokens=10)])
        assert len(streams["short"]) == 10
        assert engine.loop_launches >= 1
        # early exit: units actually run < launches * K
        assert engine.loop_units < engine.loop_launches * 4
        assert engine.allocator.blocks_in_use == 0

    def test_zero_recompiles_after_warmup(self):
        """The loop program is warmed once (all-inactive lanes, exits
        at unit 0) and never compiles again — across greedy, sampled,
        early exits and admissions between launches."""
        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        engine = self._pair(params, config, 4, top_k=10, top_p=0.95)
        engine.warmup()
        baseline = engine.compile_counts()
        assert baseline["loop"] >= 1
        rng = np.random.default_rng(77)
        self._streams(engine, [
            dict(rid="a", prompt=rng.integers(0, 64, 9),
                 max_new_tokens=13),
            dict(rid="b", prompt=rng.integers(0, 64, 17),
                 max_new_tokens=6, temperature=0.9,
                 rng=jax.random.PRNGKey(78)),
            dict(rid="c", prompt=rng.integers(0, 64, 5),
                 max_new_tokens=10),
        ])
        assert engine.loop_launches >= 1
        assert engine.compile_counts() == baseline

    def test_config_validation_is_loud(self):
        """Satellite: bad K values and incompatible combos fail at
        construction, not deep in a launch."""
        from kubeshare_tpu.serving import (DisaggRouter, EngineConfig,
                                           ServingEngine)

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        for bad in (0, -1, 3, 6):
            with pytest.raises(ValueError, match="power of two"):
                ServingEngine(params, config, EngineConfig(
                    num_slots=2, block_size=4, num_blocks=13,
                    max_request_len=32, prefill_chunk=8,
                    steps_per_launch=bad))
        with pytest.raises(ValueError, match="never runs decode"):
            ServingEngine(params, config, EngineConfig(
                num_slots=2, block_size=4, num_blocks=13,
                max_request_len=32, prefill_chunk=8, mixed=False,
                pool_role="prefill", steps_per_launch=2))
        shared = dict(block_size=4, max_request_len=32,
                      prefill_chunk=8, mixed=False)
        with pytest.raises(ValueError, match="decode_priority pacing"):
            DisaggRouter(
                params, config,
                EngineConfig(num_slots=2, num_blocks=17, **shared),
                EngineConfig(num_slots=2, num_blocks=17,
                             steps_per_launch=2, **shared),
                decode_priority=2)


class TestSpecLoop:
    """Device residency v2: drafted rounds run INSIDE the device loop —
    each unit drafts via on-device n-gram suffix match, verifies at
    width W and applies acceptance without leaving device — and the
    pending-lane admission ring activates pre-marshaled lanes at span
    boundaries when a lane retires.  The oracle is the K=1 non-loop
    speculative engine: bit-exact streams, greedy and sampled, with
    zero new compiled shapes after warmup."""

    def _engine(self, params, config, k, **overrides):
        from kubeshare_tpu.serving import EngineConfig, ServingEngine

        kwargs = dict(num_slots=3, block_size=4, num_blocks=41,
                      max_request_len=48, prefill_chunk=8,
                      speculative=True, steps_per_launch=k)
        kwargs.update(overrides)
        return ServingEngine(params, config, EngineConfig(**kwargs))

    def _streams(self, engine, reqs):
        from kubeshare_tpu.serving import Request

        for req in reqs:
            engine.submit(Request(**req))
        return {rid: r.tokens for rid, r in engine.run().items()}

    def _spec_reqs(self, n=4, new=10, sampled=()):
        """Repetitive prompts (tiled patterns) so the n-gram drafter
        proposes on every lane and decode rounds go all-drafted —
        the rounds the spec loop exists to absorb."""
        rng = np.random.default_rng(81)
        reqs = []
        for i in range(n):
            pat = rng.integers(0, 64, 4)
            prompt = np.concatenate(
                [np.tile(pat, 3), rng.integers(0, 64, 2)])
            req = dict(rid=f"r{i}", prompt=prompt, max_new_tokens=new)
            if i in sampled:
                req.update(temperature=0.8,
                           rng=jax.random.PRNGKey(82 + i))
            reqs.append(req)
        return reqs

    def test_streams_bit_exact_spec_loop_on_vs_off(self):
        """Loop-on vs loop-off, token for token, greedy AND sampled,
        across GQA and windowed attention — the bit-exactness argument
        (verification is exact-match against the engine's own pick
        policy keyed by emission number, so the device drafter's
        scheduling-only differences from the host drafter can change
        acceptance RATE, never a stream) made empirical."""
        cases = {
            "gqa_rope": dict(n_kv_heads=2, positional="rope"),
            "windowed": dict(attention_window=6),
        }
        for name, extra in cases.items():
            config = _small_config(**extra)
            params = transformer_init(jax.random.PRNGKey(0), config)
            sampled = (1, 2) if name == "gqa_rope" else ()
            kwargs = (dict(top_k=10, top_p=0.95)
                      if name == "gqa_rope" else {})
            workload = self._spec_reqs(n=3, new=12, sampled=sampled)
            on = self._engine(params, config, 4, **kwargs)
            off = self._engine(params, config, 1, **kwargs)
            got = self._streams(on, list(workload))
            want = self._streams(off, list(workload))
            assert got == want, name
            assert on.spec_loop_launches > 0, name
            assert on.spec_loop_units > 0, name
            assert off.spec_loop_launches == 0, name

    def test_admission_ring_activates_lanes_bit_exact(self):
        """More requests than slots with the ring armed: retiring lanes
        hand their slot to pre-marshaled pending lanes AT SPAN
        BOUNDARIES inside a launch (prefilled ahead, PRNG schedule
        written ahead, key index reset on activation) — and the streams
        still match the ring-off, loop-off engine exactly."""
        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        workload = self._spec_reqs(n=7, new=8, sampled=(2, 5))
        kwargs = dict(top_k=10, top_p=0.95)
        ring = self._engine(params, config, 4, admission_ring=2,
                            **kwargs)
        off = self._engine(params, config, 1, **kwargs)
        got = self._streams(ring, list(workload))
        want = self._streams(off, list(workload))
        assert got == want
        assert ring.spec_loop_launches > 0
        # ring pressure was real: either a staged lane activated inside
        # a launch or a launch exited starving (ring_empty) — both are
        # the ring path, and on this 7-request/3-slot trace at least
        # one of the two must have happened
        assert (ring.loop_exit_reasons["ring_empty"] > 0
                or ring.spec_loop_units > ring.spec_loop_launches)
        assert ring.allocator.blocks_in_use == 0
        assert ring._ring_staged == []

    def test_exit_reason_and_depth_metrics(self):
        """Satellite: every launch lands exactly one exit-reason count,
        and the realized-depth summary reports unit depth directly —
        sum = units, count = launches — so the bench reads fusion depth
        from the metrics plane instead of dividing counters."""
        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        engine = self._engine(params, config, 4, admission_ring=2)
        self._streams(engine, self._spec_reqs(n=6, new=8))
        launches = engine.loop_launches + engine.spec_loop_launches
        units = engine.loop_units + engine.spec_loop_units
        assert launches > 0
        assert sum(engine.loop_exit_reasons.values()) == launches
        assert set(engine.loop_exit_reasons) == {
            "retire", "budget", "stop", "redraft", "ring_empty"}
        assert engine.loop_depth_count == launches
        assert engine.loop_depth_sum == units
        fams = {f.name: f for f in engine.collect_metrics()}
        reasons = fams["kubeshare_serving_loop_exit_reason_total"]
        by_reason = {s.labels["reason"]: s.value for s in reasons.samples}
        assert by_reason == {k: v for k, v
                             in engine.loop_exit_reasons.items()}
        depth = fams["kubeshare_serving_loop_realized_depth"]
        vals = {s.name.rsplit("_", 1)[-1]: s.value
                for s in depth.samples}
        assert vals["sum"] == units
        assert vals["count"] == launches
        su = fams["kubeshare_serving_spec_loop_units_total"]
        assert sum(s.value for s in su.samples) == engine.spec_loop_units

    def test_zero_recompiles_after_warmup(self):
        """The verify-in-loop program (and its ring variant) is warmed
        once per loop depth and never compiles again — greedy, sampled,
        redraft exits, ring activations, admissions between launches."""
        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        engine = self._engine(params, config, 4, admission_ring=2,
                              top_k=10, top_p=0.95)
        engine.warmup()
        baseline = engine.compile_counts()
        assert baseline["spec_loop"] >= 1
        self._streams(engine, self._spec_reqs(n=6, new=9, sampled=(1, 4)))
        assert engine.spec_loop_launches > 0
        assert engine.compile_counts() == baseline

    def test_config_validation_is_loud(self):
        from kubeshare_tpu.serving import EngineConfig, ServingEngine

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        geo = dict(num_slots=2, block_size=4, num_blocks=13,
                   max_request_len=32, prefill_chunk=8)
        with pytest.raises(ValueError, match="admission_ring"):
            ServingEngine(params, config, EngineConfig(
                admission_ring=-1, **geo))
        # the ring rides the verify-in-loop launch: it needs
        # speculation, a real loop depth, and a decode-capable pool
        for bad in (dict(admission_ring=2),
                    dict(admission_ring=2, speculative=True),
                    dict(admission_ring=2, speculative=True,
                         steps_per_launch=2, mixed=False,
                         pool_role="decode")):
            with pytest.raises(ValueError, match="admission_ring"):
                ServingEngine(params, config,
                              EngineConfig(**{**geo, **bad}))


class TestServingBenchSmoke:
    def test_smoke_ratio_and_zero_recompiles(self):
        """The bench's CPU smoke path: continuous vs run-to-completion
        on a Poisson mixed-length workload, seconds-fast, recompile-free
        after warmup."""
        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "serving_bench", os.path.join(
                os.path.dirname(__file__), "..", "benchmarks",
                "serving_bench.py"))
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        result = bench.run_bench(bench.smoke_settings())
        assert result["recompiles_after_warmup"] == 0
        assert result["continuous"]["tokens_per_s"] > 0
        assert result["run_to_completion"]["tokens_per_s"] > 0
        # the smoke model is toy-sized (1 layer since the mixed-batching
        # PR trimmed the smokes' compile bill) and dispatch-bound on
        # CPU, so the ratio is noisy (~0.27-0.9 observed) and FAR under
        # the full bench's (1.75-2.06x measured — docs/perf.md); this
        # test locks the mechanics and the recompile-free property, not
        # the 1.5x criterion
        assert result["ratio"] > 0.15

    def test_multi_tenant_smoke_preempts_and_stays_bit_exact(self):
        """The --multi-tenant smoke path: Guarantee stream under an
        Opportunistic long-decode flood at one KV-HBM budget.  The tiny
        model's ratios are noisy on CPU (the full bench owns the 0.8
        retention / 2x TTFT / 0.9 aggregate criteria — docs/perf.md);
        what IS locked: the flood forces preemptions, every stream is
        bit-exact between qos-on and qos-off (the run_qos_bench-internal
        hard assert), and nothing recompiles."""
        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "serving_bench", os.path.join(
                os.path.dirname(__file__), "..", "benchmarks",
                "serving_bench.py"))
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        result = bench.run_qos_bench(bench.qos_smoke_settings())
        assert result["recompiles_after_warmup"] == 0
        assert result["streams_bit_exact"] is True
        assert result["preemptions"].get("batch", 0) >= 1
        assert result["preemptions"].get("prod", 0) == 0
        assert result["qos_on_guarantee"]["tokens_per_s"] > 0
        assert result["guarantee_retention"] > 0.25  # mechanics, not perf

    def test_mixed_smoke_fuses_and_stays_bit_exact(self):
        """The --mixed smoke path: mixed batching on vs off on a
        long-prompt/decode-mix trace.  The tiny model's timing ratios
        are noisy on CPU (the full bench owns the TBT-p99-lower /
        tokens/s-equal criteria — docs/perf.md); what IS locked: fused
        dispatches actually ran, every stream is bit-exact between the
        two schedulers (run_mixed_bench's internal hard assert), the
        TBT quantiles flow through the metrics plane, and nothing
        recompiles."""
        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "serving_bench", os.path.join(
                os.path.dirname(__file__), "..", "benchmarks",
                "serving_bench.py"))
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        result = bench.run_mixed_bench(bench.mixed_smoke_settings(),
                                       aba=False)
        assert result["recompiles_after_warmup"] == 0
        assert result["streams_bit_exact"] is True
        assert result["mixed"]["mixed_steps"] >= 1
        assert result["unmixed"]["mixed_steps"] == 0
        assert result["mixed"]["tbt_s"]["p99"] > 0
        assert result["unmixed"]["tbt_s"]["p99"] > 0
        assert result["mixed"]["tokens_per_s"] > 0

    def test_speculative_smoke_verifies_and_stays_bit_exact(self):
        """The --speculative smoke path: self-drafted verify chunks on
        vs off on the echoed phrase-pool trace.  The tiny model's
        dispatch ratio is workload-sensitive on CPU (the full bench
        owns the >=1.3x dispatches-per-token criterion — docs/perf.md);
        what IS locked: verify chunks actually ran, drafts were
        proposed and some accepted, every stream is bit-exact between
        the two arms (run_speculative_bench's internal hard assert),
        and nothing recompiles with the verify widths in play."""
        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "serving_bench", os.path.join(
                os.path.dirname(__file__), "..", "benchmarks",
                "serving_bench.py"))
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        result = bench.run_speculative_bench(bench.spec_smoke_settings(),
                                             aba=False)
        assert result["recompiles_after_warmup"] == 0
        assert result["streams_bit_exact"] is True
        assert result["speculative"]["verify_steps"] >= 1
        assert result["drafted_tokens"] > 0
        assert result["accepted_tokens"] > 0
        assert result["speculative"]["dispatches_per_token"] > 0
        assert result["sequential"]["dispatches_per_token"] > 0
        assert result["draft_acceptance_rate"] > 0

    def test_shared_prefix_smoke_skips_and_stays_compiled(self):
        """The --shared-prefix smoke path: prefix cache on vs off on a
        shared-prefix trace.  The tiny model is dispatch-bound on CPU so
        the tokens/s ratio is not asserted (the full bench owns the
        >=1.3x criterion — docs/perf.md); what IS locked: a majority of
        shared-prefix tokens skip prefill (read back via the metrics
        families) and nothing recompiles with the cache in play."""
        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "serving_bench", os.path.join(
                os.path.dirname(__file__), "..", "benchmarks",
                "serving_bench.py"))
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        result = bench.run_shared_bench(bench.shared_smoke_settings())
        assert result["recompiles_after_warmup"] == 0
        assert result["prefix_tokens_skipped_fraction"] >= 0.5
        assert result["cached"]["prefix_hit_requests"] > 0
        assert result["uncached"]["prefix_hit_tokens"] == 0
        assert result["cached"]["tokens_per_s"] > 0

    def test_disagg_smoke_migrates_and_stays_bit_exact(self):
        """The --disagg smoke path: split prefill/decode pools vs the
        monolithic mixed engine at equal total KV-HBM budget.  The tiny
        1-layer model's prefill chunks are too cheap for the timing
        ratios to mean anything on CPU (the full bench owns the
        decode-TBT-p99-lower-at-parity-tokens/s criterion —
        docs/perf.md); what IS locked: every prompt's chain migrated
        and was delivered, the pools stayed single-phase, the
        pool-labeled TBT/TTFT quantiles flow through the metrics
        plane, every stream is bit-exact vs the monolithic engine
        (run_disagg_bench's internal hard assert), and neither pool
        recompiles after warmup."""
        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "serving_bench", os.path.join(
                os.path.dirname(__file__), "..", "benchmarks",
                "serving_bench.py"))
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        s = bench.disagg_smoke_settings()
        result = bench.run_disagg_bench(s, aba=False)
        assert result["recompiles_after_warmup"] == 0
        assert result["streams_bit_exact"] is True
        mig = result["disagg"]["migration"]
        assert mig["packed"] == s["num_requests"]
        assert mig["delivered"] == mig["packed"]
        assert mig["migrated_bytes"] > 0
        assert mig["stall_s"]["count"] == mig["delivered"]
        # single-phase pools: every prefill chunk ran prefill-side,
        # every decode span decode-side (dispatch counts by pool label)
        assert result["disagg"]["prefill_chunks"] >= 1
        assert result["disagg"]["decode_steps"] >= 1
        dispatches = result["disagg"]["dispatches"]
        assert dispatches["prefill.prefill_chunk"] >= 1
        assert dispatches["decode.decode_span"] >= 1
        assert "decode.prefill_chunk" not in dispatches
        assert "prefill.decode_span" not in dispatches
        assert "prefill.mixed" not in dispatches
        assert "decode.mixed" not in dispatches
        # latency read back PromQL-style from the pool-labeled series
        assert result["disagg"]["tbt_by_pool_s"]["decode"]["p99"] > 0
        assert result["disagg"]["ttft_by_pool_s"]["prefill"]["p50"] > 0
        assert result["disagg"]["tokens_per_s"] > 0
        assert result["monolithic"]["tokens_per_s"] > 0

    def test_fabric_smoke_promotes_across_a_process_boundary(self):
        """The --fabric smoke path: the publisher's demotion cascade
        parks document blocks on the mmap disk arena, a jax-free child
        PROCESS serves the exported store over TCP, and the cold
        fabric-on arm adopts the fetched chains so first touches are
        remote-origin tier hits.  The tiny model's timing ratios are
        noisy on CPU (the full bench owns docs/perf.md's numbers);
        what IS locked: disk blocks were actually demoted, bytes
        actually crossed the process boundary, the remote-origin
        tier-hit split is nonzero, the fabric-on hit rate beats
        fabric-off, every stream is bit-exact across arms
        (run_fabric_bench's internal hard assert), and nothing
        recompiles."""
        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "serving_bench", os.path.join(
                os.path.dirname(__file__), "..", "benchmarks",
                "serving_bench.py"))
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        result = bench.run_fabric_bench(bench.fabric_smoke_settings(),
                                        aba=False)
        assert result["recompiles_after_warmup"] == 0
        assert result["streams_bit_exact"] is True
        assert result["store"]["chains"] > 0
        assert result["store"]["publisher_disk_demoted"] > 0
        assert result["fetch"]["fetches"] > 0
        assert result["fetch"]["bytes_fetched"] > 0
        assert result["fetch"]["adopted_blocks"] > 0
        assert result["remote_tier_hits"] > 0
        assert result["fabric_on"]["tier_hit_origin"]["remote"] > 0
        assert result["hit_rate"]["fabric_on"] \
            > result["hit_rate"]["fabric_off"]
        assert result["fabric_on"]["tokens_per_s"] > 0


class TestDiskTier:
    """The mmap-backed DISK tier below host RAM (serving/kv_tier.py
    DiskTier + the engine's HOST→DISK demotion cascade and
    DISK→HOST→device promotion staging): arena round-trips are byte
    identical, the byte budget refuses and evicts like the host store,
    disk-tier-on streams are bit-exact with tier-off, and the gauges
    land on the metrics plane."""

    def _reqs(self, rng, shared):
        return [
            dict(rid="r0", prompt=shared, max_new_tokens=3),
            dict(rid="f1", prompt=rng.integers(0, 64, 29),
                 max_new_tokens=3),
            dict(rid="f2", prompt=rng.integers(0, 64, 29),
                 max_new_tokens=3),
            dict(rid="hit", prompt=np.concatenate(
                [shared, rng.integers(0, 64, 4)]), max_new_tokens=3),
        ]

    def _run_sequentially(self, engine, reqs):
        from kubeshare_tpu.serving import Request

        out = {}
        for req in reqs:
            engine.submit(Request(**req))
            out.update({rid: r.tokens for rid, r in engine.run().items()
                        if r.done})
            engine.pop_finished()
        return out

    def _disk_engine(self, params, config, **over):
        from kubeshare_tpu.serving import (EngineConfig, ServingEngine,
                                           wire_block_bytes)

        full_wire = wire_block_bytes(4, config.n_layers, config.kv_heads,
                                     4, config.head_dim, 4)
        kwargs = dict(num_slots=1, block_size=4, num_blocks=13,
                      max_request_len=32, prefill_chunk=8,
                      host_tier_bytes=3 * full_wire,
                      disk_tier_bytes=1 << 20)
        kwargs.update(over)
        return ServingEngine(params, config, EngineConfig(**kwargs))

    def test_arena_roundtrip_budget_and_hole_reuse(self):
        """The store itself: put/read/take are byte identical through
        the mmap (including across a growth re-map), the PAYLOAD-byte
        budget evicts LRU (never pins) and refuses oversized blocks,
        and freed extents coalesce for reuse."""
        from kubeshare_tpu.serving import DiskTier

        tier = DiskTier(budget_bytes=300)
        a = tier.put(b"a" * 100, None, None)
        b = tier.put(b"b" * 100, None, None)
        c = tier.put(b"c" * 100, None, None)
        assert tier.read(a) == b"a" * 100
        assert tier.used_bytes == 300
        # budget full: the next put evicts the coldest (b — a was
        # touched by the read above)
        d = tier.put(b"d" * 100, None, None)
        assert tier.probe(b) is None and tier.evicted_blocks == 1
        assert tier.read(d) == b"d" * 100
        # take() promotes: bytes come back identical, space frees
        assert tier.take(c) == b"c" * 100
        assert tier.promoted_blocks == 1 and tier.used_bytes == 200
        # pinned entries are never victims; an all-pinned store refuses
        for key in (a, d):
            tier.pin(key)
        e = tier.put(b"e" * 100, None, None)
        assert e is not None  # c's hole funds it without eviction
        tier.pin(e)
        assert tier.put(b"f" * 100, None, None) is None
        assert tier.refused_blocks == 1
        # over-budget payloads are refused up front
        assert tier.put(b"x" * 301, None, None) is None
        # growth re-map preserves existing payloads bit for bit
        big = DiskTier(budget_bytes=1 << 22)
        k1 = big.put(b"q" * 37, None, None)
        k2 = big.put(b"z" * (1 << 20), None, None)  # forces _grow
        assert big.read(k1) == b"q" * 37
        assert big.read(k2) == b"z" * (1 << 20)
        tier.close()
        big.close()

    def test_named_arena_file_is_a_real_mmap_file(self, tmp_path):
        """disk_tier_path pins the arena to a caller-named file — the
        bench's cross-process handle; payloads placed through it read
        back byte identical from a fresh mmap of the same file."""
        import mmap as _mmap
        import os as _os

        from kubeshare_tpu.serving import DiskTier

        path = str(tmp_path / "kv.arena")
        tier = DiskTier(budget_bytes=1 << 16, path=path)
        payload = bytes(np.random.default_rng(0).integers(
            0, 256, 777, dtype=np.uint8))
        key = tier.put(payload, None, None)
        entry = tier.probe(key)
        fd = _os.open(path, _os.O_RDONLY)
        try:
            mm = _mmap.mmap(fd, 0, prot=_mmap.PROT_READ)
            assert bytes(mm[entry.offset: entry.offset
                            + entry.nbytes]) == payload
            mm.close()
        finally:
            _os.close(fd)
        tier.close()

    def test_streams_bit_exact_with_disk_tier_across_configs(self):
        """Disk tier on vs everything off, token for token, through a
        forced HOST→DISK→HOST→device cascade (the host budget takes 3
        wire blocks, the flushers demote 8+) — GQA and windowed
        attention included."""
        cases = {
            "plain": dict(),
            "gqa_rope": dict(n_kv_heads=2, positional="rope"),
            "windowed": dict(attention_window=6),
        }
        rng = np.random.default_rng(11)
        shared = rng.integers(0, 64, 13)
        reqs = self._reqs(rng, shared)
        for name, extra in cases.items():
            config = _small_config(**extra)
            params = transformer_init(jax.random.PRNGKey(0), config)
            disked = self._disk_engine(params, config)
            plain = self._disk_engine(params, config,
                                      host_tier_bytes=None,
                                      disk_tier_bytes=None)
            got = self._run_sequentially(disked, reqs)
            want = self._run_sequentially(plain, reqs)
            assert got == want, name
            assert disked.disk_tier.stored_blocks > 0, name
            assert disked.disk_tier.promoted_blocks > 0, name
            assert disked.tier_hit_requests_by_origin["local"] >= 1

    def test_sampled_streams_bit_exact_with_disk_tier(self):
        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        rng = np.random.default_rng(13)
        shared = rng.integers(0, 64, 13)
        reqs = []
        for i, req in enumerate(self._reqs(rng, shared)):
            req.update(temperature=0.8, rng=jax.random.PRNGKey(40 + i))
            reqs.append(req)
        disked = self._disk_engine(params, config, top_k=10)
        plain = self._disk_engine(params, config, top_k=10,
                                  host_tier_bytes=None,
                                  disk_tier_bytes=None)
        got = self._run_sequentially(disked, reqs)
        want = self._run_sequentially(plain, reqs)
        assert got == want
        assert disked.disk_tier.promoted_blocks > 0

    def test_zero_recompiles_with_disk_promotions(self):
        """The cascade adds no dispatch shapes: promotion from disk
        rides the SAME warmed upload path a host hit uses."""
        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        engine = self._disk_engine(params, config)
        engine.warmup()
        baseline = engine.compile_counts()
        rng = np.random.default_rng(37)
        shared = rng.integers(0, 64, 13)
        self._run_sequentially(engine, self._reqs(rng, shared))
        assert engine.disk_tier.promoted_blocks > 0
        assert engine.compile_counts() == baseline

    def test_disk_gauges_on_metrics_plane(self):
        from kubeshare_tpu.serving import flatten_metrics, metric_value

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        engine = self._disk_engine(params, config)
        rng = np.random.default_rng(11)
        shared = rng.integers(0, 64, 13)
        self._run_sequentially(engine, self._reqs(rng, shared))
        fams = flatten_metrics(engine.collect_metrics())
        assert metric_value(fams, "kubeshare_serving_disk_tier_blocks_total",
                            event="demoted") > 0
        assert metric_value(fams, "kubeshare_serving_disk_tier_blocks_total",
                            event="promoted") > 0
        assert metric_value(fams, "kubeshare_serving_disk_tier_bytes",
                            kind="budget") == 1 << 20
        assert metric_value(fams, "kubeshare_serving_disk_tier_bytes",
                            kind="used") >= 0
        # the remote-vs-local tier-hit split is on the plane too
        assert metric_value(
            fams, "kubeshare_serving_tier_hit_origin_requests_total",
            origin="local") >= 1
        assert metric_value(
            fams, "kubeshare_serving_tier_hit_origin_requests_total",
            origin="remote") == 0

    def test_config_validation_is_loud(self):
        from kubeshare_tpu.serving import EngineConfig, ServingEngine

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        with pytest.raises(ValueError, match="requires host_tier_bytes"):
            ServingEngine(params, config, EngineConfig(
                num_slots=1, block_size=4, num_blocks=13,
                max_request_len=32, disk_tier_bytes=1 << 20))
        with pytest.raises(ValueError, match="disk_tier_path"):
            ServingEngine(params, config, EngineConfig(
                num_slots=1, block_size=4, num_blocks=13,
                max_request_len=32, host_tier_bytes=1 << 20,
                disk_tier_path="/tmp/x.arena"))


class TestFabric:
    """The cluster KV fabric (serving/fabric.py): envelope honesty
    (crc-first, loud corruption), bit-identical chain round-trips over
    a REAL socketpair, at-least-once endpoint delivery with ack/dedup/
    TTL/bounded backoff, the prefix directory's remote-affinity hook in
    fleet routing, drain inheritance riding the fabric, the disagg
    ticket bus, and the exportable prefix store."""

    def test_message_envelope_roundtrip_and_corruption(self):
        from kubeshare_tpu.serving import (WireCorruption, pack_message,
                                           unpack_message)
        from kubeshare_tpu.serving.fabric import K_CHAIN

        body = b"\x01payload bytes\xff" * 9
        frame = pack_message(K_CHAIN, 42, "alpha", "beta", body)
        kind, mid, src, dest, got = unpack_message(frame)
        assert (kind, mid, src, dest, got) == (
            K_CHAIN, 42, "alpha", "beta", body)
        # any single flipped bit — header, body, crc trailer — is a
        # typed WireCorruption, checked BEFORE any envelope field
        for at in (0, 3, 11, len(frame) // 2, len(frame) - 1):
            bad = bytearray(frame)
            bad[at] ^= 0x10
            with pytest.raises(WireCorruption):
                unpack_message(bytes(bad))
        with pytest.raises(WireCorruption, match="truncated"):
            unpack_message(frame[:8])
        # intact-but-foreign frames are plain ValueErrors (re-sealed so
        # the crc passes and the magic/version checks are reachable)
        import struct as _struct
        import zlib as _zlib

        def reseal(b: bytes) -> bytes:
            return b[:-4] + _struct.pack(
                "<I", _zlib.crc32(b[:-4]) & 0xFFFFFFFF)

        with pytest.raises(ValueError, match="magic"):
            unpack_message(reseal(b"XXXX" + frame[4:]))
        with pytest.raises(ValueError, match="version"):
            unpack_message(reseal(frame[:4] + b"\x63\x00" + frame[6:]))
        with pytest.raises(ValueError, match="over 16 bytes"):
            pack_message(K_CHAIN, 0, "x" * 17, "beta", b"")

    def test_chain_roundtrip_over_socketpair_bit_identical(self):
        """Satellite wire-honesty lock: a packed prefix chain crosses a
        REAL OS socketpair and unpacks to byte-identical payloads and
        device rows — float32 and bfloat16 — and a single flipped bit
        anywhere in the frame is a loud WireCorruption on the far
        side.  Locked against the v2 block format fixtures."""
        import socket as _socket

        from kubeshare_tpu.serving import (KV_WIRE_VERSION,
                                           WireCorruption, pack_block,
                                           pack_message, recv_frame,
                                           send_frame, unpack_block,
                                           unpack_message)
        from kubeshare_tpu.serving.fabric import (K_CHAIN,
                                                  pack_chain_msg,
                                                  unpack_chain_msg)

        assert KV_WIRE_VERSION == 2
        rng = np.random.default_rng(7)
        items = []
        toks = rng.integers(0, 64, 8).astype(np.int32)
        for i, dt in enumerate((np.float32, jnp.bfloat16)):
            k = np.asarray(
                rng.standard_normal((2, 2, 4, 8)).astype(np.float32))
            k = np.asarray(jnp.asarray(k, dt)) if dt is jnp.bfloat16 \
                else k
            # cumulative root-to-node token path, per-BLOCK payload
            payload = pack_block(toks[4 * i: 4 * (i + 1)], k, k)
            items.append((toks[:4 * (i + 1)], payload))
        frame = pack_message(
            K_CHAIN, 0, "sender", "receiver",
            pack_chain_msg("tenant-a", items))

        a, b = _socket.socketpair()
        try:
            send_frame(a, frame)
            got_frame = recv_frame(b)
            assert got_frame == frame  # the transport is byte-honest
            _, _, _, _, body = unpack_message(got_frame)
            tenant, got_items = unpack_chain_msg(body)
            assert tenant == "tenant-a"
            assert len(got_items) == len(items)
            for (toks0, pay0), (toks1, pay1) in zip(items, got_items):
                assert np.array_equal(toks0, toks1)
                assert pay0 == pay1  # byte identical through the wire
                t0, k0, v0 = unpack_block(pay0)
                t1, k1, v1 = unpack_block(pay1)
                assert np.array_equal(t0, t1)
                assert k0.dtype == k1.dtype
                assert np.array_equal(k0.view(np.uint8),
                                      k1.view(np.uint8))
                assert np.array_equal(v0.view(np.uint8),
                                      v1.view(np.uint8))
            # a flipped bit in transit is LOUD on the receiving side
            bad = bytearray(frame)
            bad[len(bad) // 2] ^= 0x01
            send_frame(a, bytes(bad))
            with pytest.raises(WireCorruption):
                unpack_message(recv_frame(b))
        finally:
            a.close()
            b.close()

    def test_chain_survives_disk_arena_byte_identical(self):
        """The same honesty through the mmap file: a wire-v2 payload
        parked in the DISK arena reads back byte identical, and a
        rotted byte on the platter is a WireCorruption at unpack."""
        from kubeshare_tpu.serving import (DiskTier, WireCorruption,
                                           pack_block, unpack_block)

        rng = np.random.default_rng(9)
        k = rng.standard_normal((2, 2, 4, 8)).astype(np.float32)
        payload = pack_block(np.arange(4, dtype=np.int32), k, k)
        tier = DiskTier(budget_bytes=1 << 16)
        key = tier.put(payload, None, None)
        assert tier.read(key) == payload
        t2, k2, v2 = unpack_block(tier.read(key))
        assert np.array_equal(k2, k) and np.array_equal(v2, k)
        # rot the platter directly (no chaos clock): loud at unpack
        entry = tier.probe(key)
        tier._mm[entry.offset + 11] ^= 0x20
        with pytest.raises(WireCorruption):
            unpack_block(tier.read(key))
        tier.close()

    def test_endpoint_ack_dedup_redelivery_and_ttl(self):
        """The at-least-once contract end to end: a dropped frame is
        retransmitted under bounded backoff and delivered exactly once;
        a dropped ACK triggers a redelivery the receiver absorbs as a
        duplicate (re-acking it); a partitioned destination expires
        after ttl_ticks and surfaces through take_expired."""
        from kubeshare_tpu.serving import (FabricEndpoint,
                                           LoopbackTransport)
        from kubeshare_tpu.serving.fabric import K_CHAIN

        class _Flaky(LoopbackTransport):
            def __init__(self):
                super().__init__()
                self.drop_next = 0

            def send(self, dest, frame):
                if self.drop_next > 0:
                    self.drop_next -= 1
                    return
                super().send(dest, frame)

        tr = _Flaky()
        a = FabricEndpoint("a", tr, ttl_ticks=8)
        b = FabricEndpoint("b", tr, ttl_ticks=8)
        # 1) dropped data frame -> backoff redelivery -> one delivery
        tr.drop_next = 1
        mid = a.send("b", K_CHAIN, b"hello")
        assert b.poll() == [] and a.inflight == 1
        a.tick()  # due: retransmit
        got = b.poll()
        assert [(s, k, m, body) for s, k, m, body in got] == [
            ("a", K_CHAIN, mid, b"hello")]
        assert a.poll() == []  # acks are absorbed, not surfaced
        assert a.take_delivered() == [mid] and a.inflight == 0
        assert a.redeliveries == 1
        # 2) dropped ACK -> redelivery -> receiver dedups and re-acks
        mid2 = a.send("b", K_CHAIN, b"again")
        tr.drop_next = 1  # the ack is the next frame b sends
        assert len(b.poll()) == 1
        assert a.poll() == [] and a.inflight == 1  # ack lost
        a.tick()
        assert b.poll() == []  # duplicate absorbed, re-acked
        assert b.messages[("chain", "duplicate")] == 1
        a.poll()
        assert a.take_delivered() == [mid2] and a.inflight == 0
        # 3) partition: every transmit dropped until TTL
        tr.drop_next = 10 ** 6
        mid3 = a.send("b", K_CHAIN, b"doomed")
        for _ in range(8):
            a.tick()
        assert a.inflight == 0
        assert a.take_expired() == [("b", K_CHAIN, mid3, b"doomed")]
        assert a.messages[("chain", "expired")] == 1
        # counters reconcile: delivered + expired == sent
        assert (a.messages[("chain", "delivered")]
                + a.messages[("chain", "expired")]
                == a.messages[("chain", "sent")])

    def test_ticket_body_roundtrip(self):
        from kubeshare_tpu.serving import pack_ticket, unpack_ticket

        keys = np.asarray([[1, 2], [3, 4]], np.uint32)
        body = pack_ticket(
            "rid-1", "tenant-b", np.arange(7, dtype=np.int32), 11, 5,
            0.8, keys, b"\x00wire\xff", [11, 3], np.asarray([3, 1],
                                                            np.int32),
            0.25, last_token_at=123.5)
        d = unpack_ticket(body)
        assert d["rid"] == "rid-1" and d["tenant"] == "tenant-b"
        assert np.array_equal(d["prompt"], np.arange(7))
        assert (d["first_token"], d["max_new"]) == (11, 5)
        assert d["temperature"] == 0.8
        assert np.array_equal(d["step_keys"], keys)
        assert d["payload"] == b"\x00wire\xff"
        assert d["emitted_prefix"] == [11, 3]
        assert list(d["hint"]) == [3, 1]
        assert d["pack_stall_s"] == 0.25
        assert d["last_token_at"] == 123.5
        # greedy: empty key schedule, no hint, no last-token timestamp
        d2 = unpack_ticket(pack_ticket(
            "r", "t", np.asarray([1], np.int32), 0, 1, 0.0,
            np.zeros((0, 0), np.uint32), b"", [], np.asarray([],
                                                             np.int32),
            0.0))
        assert d2["step_keys"].size == 0 and d2["hint"].size == 0
        assert d2["last_token_at"] is None

    def test_remote_affinity_routes_via_directory(self):
        """A trie miss everywhere + a directory hit routes to the
        publishing owner (reason remote_affinity) instead of
        least-loaded — the fabric's re-prefill saver."""
        from kubeshare_tpu.serving import (EngineConfig, ReplicaFleet,
                                           Request)
        from kubeshare_tpu.serving.fabric import (LoopbackTransport,
                                                  prefix_fabric_key)

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        fleet = ReplicaFleet(
            params, config,
            EngineConfig(num_slots=3, block_size=4, num_blocks=21,
                         max_request_len=48, prefill_chunk=8),
            replicas=2, shared_tier_bytes=1 << 20,
            fabric=LoopbackTransport())
        rng = np.random.default_rng(3)
        prompt = rng.integers(0, 64, 14)
        target = fleet.replicas[1].name
        # publish the 12-token block boundary as held by replica 1
        fleet.directory.publish(prefix_fabric_key(prompt[:12]), target,
                                token_len=12)
        fleet.submit(Request("q", prompt, 3))
        fleet.run()
        assert fleet.owner_of("q") == target
        assert fleet.routing_decisions["remote_affinity"] == 1
        # a withdrawn owner falls back to least-loaded (staleness-safe)
        fleet.directory.withdraw_owner(target)
        fleet.submit(Request("q2", rng.integers(0, 64, 14), 3))
        fleet.run()
        assert fleet.routing_decisions["remote_affinity"] == 1

    def test_fleet_drain_inheritance_rides_the_fabric(self):
        """The PR-16 drain test, fabric edition: the retiree's trie
        crosses to the survivor as acked K_CHAIN messages (counted,
        metered), the directory learns the adopter, and the heir
        request promotes remotely-adopted host blocks — visible in the
        remote-vs-local tier-hit split."""
        from kubeshare_tpu.serving import (EngineConfig, ReplicaFleet,
                                           Request, flatten_metrics,
                                           metric_value)
        from kubeshare_tpu.serving.fabric import LoopbackTransport

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        fleet = ReplicaFleet(
            params, config,
            EngineConfig(num_slots=3, block_size=4, num_blocks=21,
                         max_request_len=48, prefill_chunk=8),
            replicas=2, shared_tier_bytes=1 << 20,
            fabric=LoopbackTransport(), fabric_ttl_ticks=8)
        fleet.warmup()
        rng = np.random.default_rng(11)
        shared = rng.integers(0, 64, 16)

        def req(rid):
            return Request(rid, np.concatenate(
                [shared, rng.integers(0, 64, 4)]), 4)

        fleet.submit(req("seed"))
        fleet.run()
        owner = fleet.owner_of("seed")
        survivor = [h for h in fleet.replicas if h.name != owner][0]
        assert survivor.engine.prefix_match_len(shared) == 0
        fleet.drain(owner)
        fleet.run()
        assert fleet._handle(owner).state == "retired"
        assert survivor.engine.prefix_match_len(shared) >= 16
        assert fleet.fabric_adopted_tokens > 0
        assert len(fleet.directory) > 0
        # the retiree's endpoint is gone; nothing is left in flight
        assert owner not in fleet._endpoints
        fleet.submit(req("heir"))
        fleet.run()
        assert fleet.owner_of("heir") == survivor.name
        flat = flatten_metrics(fleet.collect_metrics())
        delivered = metric_value(
            flat, "kubeshare_serving_fabric_messages_total",
            kind="chain", outcome="delivered")
        sent = metric_value(
            flat, "kubeshare_serving_fabric_messages_total",
            kind="chain", outcome="sent")
        assert delivered > 0 and delivered == sent
        assert metric_value(
            flat, "kubeshare_serving_fabric_bytes_total") > 0
        assert metric_value(
            flat, "kubeshare_serving_fabric_chain_tokens_adopted_total"
        ) == fleet.fabric_adopted_tokens
        # the heir's promotion is charged to the REMOTE origin bucket
        assert metric_value(
            flat, "kubeshare_serving_tier_hit_origin_requests_total",
            origin="remote") >= 1

    def test_disagg_tickets_ride_the_fabric_bit_exact(self):
        """Handoff tickets as fabric messages: the split-pool router
        with a loopback fabric emits EXACTLY the monolithic streams —
        greedy and sampled — and every ticket is acked (delivered ==
        sent, nothing in flight at drain)."""
        from kubeshare_tpu.serving import (DisaggRouter, EngineConfig,
                                           Request, ServingEngine,
                                           flatten_metrics,
                                           metric_value)
        from kubeshare_tpu.serving.fabric import LoopbackTransport

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)

        def reqs():
            return [Request(
                f"r{i}", np.arange(3 + i * 2) % 60, 8,
                temperature=(0.0 if i % 2 else 0.7),
                rng=(None if i % 2 else jax.random.PRNGKey(100 + i)))
                for i in range(5)]

        mono = ServingEngine(params, config, EngineConfig(
            num_slots=3, block_size=4, num_blocks=41,
            max_request_len=48, prefill_chunk=8, mixed=False))
        for r in reqs():
            mono.submit(r)
        want = {rid: res.tokens for rid, res in mono.run().items()}
        router = DisaggRouter(
            params, config,
            EngineConfig(num_slots=2, block_size=4, num_blocks=17,
                         max_request_len=48, prefill_chunk=8,
                         mixed=False),
            EngineConfig(num_slots=3, block_size=4, num_blocks=25,
                         max_request_len=48, prefill_chunk=8,
                         mixed=False),
            fabric=LoopbackTransport(), fabric_ttl_ticks=8)
        for r in reqs():
            router.submit(r)
        got = {rid: res.tokens for rid, res in router.run().items()}
        assert got == want
        assert router._fabric_inflight == {}
        assert router._fabric_arrivals == []
        flat = flatten_metrics(router.collect_metrics())
        sent = metric_value(flat,
                            "kubeshare_serving_fabric_messages_total",
                            kind="ticket", outcome="sent")
        assert sent == 5
        assert metric_value(flat,
                            "kubeshare_serving_fabric_messages_total",
                            kind="ticket", outcome="delivered") == sent

    def test_prefix_store_export_serve_fetch(self, tmp_path):
        """The cross-process promotion path's parts: export a
        disk/host-resident trie to a store file, serve it over TCP,
        fetch a chain back byte identical, and adopt it into a COLD
        engine whose next request is a tier hit instead of a
        re-prefill."""
        import threading

        from kubeshare_tpu.serving import (EngineConfig, PrefixStoreClient,
                                           Request, ServingEngine,
                                           export_prefix_store,
                                           load_prefix_store,
                                           serve_prefix_store,
                                           wire_block_bytes)
        from kubeshare_tpu.serving.fabric import (prefix_fabric_key,
                                                  unpack_prefix_blocks)
        from kubeshare_tpu.serving.kv_tier import adopt_into

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        full_wire = wire_block_bytes(4, config.n_layers, config.kv_heads,
                                     4, config.head_dim, 4)

        def engine(**over):
            kw = dict(num_slots=1, block_size=4, num_blocks=13,
                      max_request_len=32, prefill_chunk=8,
                      host_tier_bytes=1 << 20)
            kw.update(over)
            return ServingEngine(params, config, EngineConfig(**kw))

        rng = np.random.default_rng(11)
        shared = rng.integers(0, 64, 13)
        warm = engine()
        for rid, prompt in (("r0", shared),
                            ("f1", rng.integers(0, 64, 29)),
                            ("f2", rng.integers(0, 64, 29))):
            warm.submit(Request(rid, prompt, 3))
            warm.run()
            warm.pop_finished()

        def payload_of(node):
            if node.host_key is not None:
                e = warm.host_tier.probe(node.host_key)
                return None if e is None else e.payload
            if node.disk_key is not None:
                return warm.disk_tier.read(node.disk_key)
            if node.block is not None and node.block >= 0:
                # live exporter: serialize device rows on the fly (the
                # bench snapshots after demotion instead)
                return warm._read_block_payload(node)
            return None

        path = str(tmp_path / "prefixes.kvps")
        manifest = export_prefix_store(warm.prefix_index, payload_of,
                                       path)
        assert len(manifest) > 0
        store = load_prefix_store(path)
        assert set(store) == {k for k, _ in manifest}
        # serve over real TCP (same-process thread; the bench does the
        # fork) and fetch the longest chain back
        import contextlib
        import io
        import time

        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            t = threading.Thread(target=serve_prefix_store,
                                 args=(path,), daemon=True)
            t.start()
            deadline = time.time() + 10
            while "PORT" not in buf.getvalue():
                assert time.time() < deadline, "store never bound"
                time.sleep(0.01)
        port = int(buf.getvalue().split()[1])
        key, token_len = max(manifest, key=lambda kv: kv[1])
        client = PrefixStoreClient(port)
        chain = client.fetch(key)
        assert chain and unpack_prefix_blocks(store[key])[-1][1] \
            == chain[-1][1]
        assert client.fetch(b"\x00" * 16) == []  # unknown key: empty
        client.close()
        t.join(timeout=10)
        # adopt the fetched chain into a COLD engine: its next request
        # over the same prefix is a tier hit, not a re-prefill
        cold = engine()
        toks, _ = chain[-1]
        assert cold.prefix_match_len(toks) == 0
        for ctoks, payload in chain:
            adopt_into(cold.host_tier, cold.prefix_index, ctoks,
                       payload, None, origin="remote")
        assert cold.prefix_match_len(toks) == len(toks)
        assert prefix_fabric_key(toks) == key
