"""Serving subsystem tests: paged KV cache, continuous batching, ragged
prefill buckets.

The contract under test is the strongest one a serving stack can make:
the paged pool + continuous-batching engine must emit EXACTLY the token
stream the dense-cache reference paths emit — per request, regardless of
what else is co-batched in the pool, which slot the request landed in,
or whose blocks it recycled.  Plus the allocator's loud-failure
discipline and the zero-recompile property the TPU serving story depends
on.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeshare_tpu.models.transformer import TransformerConfig, transformer_init

pytestmark = pytest.mark.serving


def _small_config(**extra):
    return TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=64, dtype=jnp.float32, attention="reference", **extra)


def _engine(params, config, **overrides):
    from kubeshare_tpu.serving import EngineConfig, ServingEngine

    kwargs = dict(num_slots=3, block_size=4, num_blocks=41,
                  max_request_len=48, prefill_chunk=8)
    kwargs.update(overrides)
    return ServingEngine(params, config, EngineConfig(**kwargs))


class TestBlockAllocator:
    def test_exhaustion_is_loud_and_all_or_nothing(self):
        from kubeshare_tpu.serving import BlockAllocator, BlockExhausted

        alloc = BlockAllocator(num_blocks=5, block_size=4)  # 4 allocatable
        got = alloc.reserve(3, "a")
        assert len(got) == 3 and 0 not in got
        with pytest.raises(BlockExhausted, match="needs 2 blocks"):
            alloc.reserve(2, "b")
        # the failed reservation granted NOTHING
        assert alloc.free_blocks == 1
        assert alloc.blocks_in_use == 3

    def test_double_free_raises(self):
        from kubeshare_tpu.serving import BlockAllocator

        alloc = BlockAllocator(num_blocks=5, block_size=4)
        blocks = alloc.reserve(2, "a")
        alloc.reclaim(blocks)
        with pytest.raises(ValueError, match="double free"):
            alloc.reclaim(blocks)
        with pytest.raises(ValueError, match="not allocated"):
            alloc.reclaim([0])  # the scratch block is never allocated

    def test_reclaimed_blocks_are_reused_first(self):
        from kubeshare_tpu.serving import BlockAllocator

        alloc = BlockAllocator(num_blocks=9, block_size=4)
        first = alloc.reserve(3, "a")
        alloc.reclaim(first)
        again = alloc.reserve(3, "b")
        # LIFO free list: the retired request's blocks come back first
        assert set(again) == set(first)

    def test_blocks_for_tokens(self):
        from kubeshare_tpu.serving import BlockAllocator

        alloc = BlockAllocator(num_blocks=9, block_size=4)
        assert [alloc.blocks_for_tokens(n) for n in (1, 4, 5, 8, 9)] == [
            1, 1, 2, 2, 3]


class TestPagedEquivalence:
    """Greedy and sampled streams from the paged pool must match the
    dense cache exactly — the bit-exactness the ISSUE's read path
    promises, locked at the emitted-token level."""

    def test_greedy_matches_dense_across_configs(self):
        from kubeshare_tpu.models.decoding import greedy_decode
        from kubeshare_tpu.serving import Request

        cases = {
            "mha": dict(),
            "gqa_rope": dict(n_kv_heads=2, positional="rope"),
            "windowed": dict(attention_window=6),
            "moe": dict(moe_every=2, moe_num_experts=4, moe_top_k=2),
        }
        for name, extra in cases.items():
            config = _small_config(**extra)
            params = transformer_init(jax.random.PRNGKey(0), config)
            prompt = np.asarray(jax.random.randint(
                jax.random.PRNGKey(1), (13,), 0, 64), np.int32)
            dense = np.asarray(greedy_decode(
                params, config, jnp.asarray(prompt)[None], 8))[0]
            engine = _engine(params, config)
            engine.submit(Request("r0", prompt, 8))
            out = engine.run()["r0"]
            assert out.tokens == list(dense), name

    def test_sampled_matches_dense(self):
        """Same rng => the engine reproduces sample_decode_with_cache's
        stream exactly (temperature + top-k + top-p filtered)."""
        from kubeshare_tpu.models.decoding import sample_decode
        from kubeshare_tpu.serving import Request

        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        prompt = np.asarray(jax.random.randint(
            jax.random.PRNGKey(1), (10,), 0, 64), np.int32)
        rng = jax.random.PRNGKey(7)
        dense = np.asarray(sample_decode(
            params, config, jnp.asarray(prompt)[None], rng, 6,
            temperature=0.8, top_k=10, top_p=0.95))[0]
        engine = _engine(params, config, top_k=10, top_p=0.95)
        engine.submit(Request("r0", prompt, 6, temperature=0.8, rng=rng))
        out = engine.run()["r0"]
        assert out.tokens == list(dense)

    def test_paged_pool_rows_match_dense_cache(self):
        """Below the token level: the slot's gathered K/V rows equal the
        dense cache's rows after the same prefill."""
        from kubeshare_tpu.models.decoding import prefill
        from kubeshare_tpu.serving import Request, paged_gather_kv

        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        prompt = np.asarray(jax.random.randint(
            jax.random.PRNGKey(2), (11,), 0, 64), np.int32)
        dense_cache, _ = prefill(params, config, jnp.asarray(prompt)[None])
        engine = _engine(params, config)
        engine.submit(Request("r0", prompt, 1))
        engine.run()
        # request retired, but its writes are still in the pool; rebuild
        # its view through the blocks it was using (LIFO: re-reserve)
        blocks = engine.allocator.reserve(
            engine.allocator.blocks_for_tokens(12), "probe")
        table = np.zeros(engine._table_width, np.int32)
        # the original table listed blocks in reservation order; the
        # LIFO reclaim + re-reserve hands them back reversed
        table[: len(blocks)] = list(reversed(blocks))
        k_view, _ = paged_gather_kv(engine.pool.k, engine.pool.v,
                                    jnp.asarray(table))
        np.testing.assert_allclose(
            np.asarray(k_view[:, :, :11]),
            np.asarray(dense_cache["k"][:, 0, :, :11]),
            rtol=1e-6, atol=1e-6)


class TestContinuousBatching:
    def test_mixed_lengths_match_solo_references(self):
        """The killer property: 10 mixed-length requests squeezed
        through 3 slots — admitted mid-flight, recycling retired slots'
        blocks — each emit exactly their SOLO dense-path stream."""
        from kubeshare_tpu.models.decoding import greedy_decode
        from kubeshare_tpu.serving import Request

        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        rng = np.random.default_rng(3)
        # 7 requests over 3 slots; lengths chosen to hit full-chunk,
        # ragged-tail, and short-pad prefill plans (repeated (L, new)
        # pairs keep the dense-reference compile count down — tier-1
        # time is compile-dominated at this model size)
        shapes = [(1, 3), (5, 8), (13, 4), (21, 11), (5, 8), (13, 4),
                  (29, 2)]
        reqs = [(f"r{i}", rng.integers(0, 64, length), new)
                for i, (length, new) in enumerate(shapes)]
        engine = _engine(params, config)
        for rid, prompt, new in reqs:
            engine.submit(Request(rid, prompt, new))
        out = engine.run()
        for rid, prompt, new in reqs:
            ref = np.asarray(greedy_decode(
                params, config, jnp.asarray(prompt, jnp.int32)[None], new))[0]
            assert out[rid].tokens == list(ref), rid
        # every retired request's blocks went home
        assert engine.allocator.blocks_in_use == 0
        assert engine.allocator.free_blocks == engine.allocator.num_blocks - 1
        # a live-loop server evicts completed results instead of letting
        # the result map grow with every request ever served
        popped = engine.pop_finished()
        assert sorted(popped) == sorted(rid for rid, _, _ in reqs)
        assert engine.pop_finished() == {}
        # and the pool was actually oversubscribed: peak in-use is under
        # what 10 requests would need simultaneously
        total_demand = sum(
            engine.allocator.blocks_for_tokens(len(p) + n)
            for _, p, n in reqs)
        assert 0 < engine.peak_blocks_in_use < total_demand

    def test_admission_waits_on_block_exhaustion(self):
        """A request the pool can't fund YET queues (no clamp, no drop)
        and admits after a retirement frees blocks; a request that can
        NEVER fit fails loudly at submit."""
        from kubeshare_tpu.serving import BlockExhausted, Request

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        # 6 allocatable blocks x 4 = 24 rows total
        engine = _engine(params, config, num_slots=2, num_blocks=7,
                         max_request_len=32)
        prompt = np.zeros(17, np.int32)  # 17 + 3 -> 5 blocks each
        engine.submit(Request("big0", prompt, 3))
        engine.submit(Request("big1", prompt, 3))
        engine.step()  # admits big0 (5 blocks); big1 (5 > 3 free) waits
        assert engine.result("big0").admitted_at is not None
        assert engine.result("big1").admitted_at is None
        out = engine.run()  # big0 retires -> big1 admits and completes
        assert len(out["big1"].tokens) == 3
        with pytest.raises(BlockExhausted, match="NEVER"):
            engine.submit(Request("huge", np.zeros(30, np.int32), 2))

    def test_submit_validation_is_loud(self):
        from kubeshare_tpu.serving import Request

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        engine = _engine(params, config)
        with pytest.raises(ValueError, match="max_new_tokens"):
            engine.submit(Request("a", np.zeros(4, np.int32), 0))
        with pytest.raises(ValueError, match="max_request_len"):
            engine.submit(Request("b", np.zeros(40, np.int32), 20))
        with pytest.raises(ValueError, match="rng"):
            engine.submit(Request("c", np.zeros(4, np.int32), 2,
                                  temperature=0.7))
        with pytest.raises(ValueError, match="non-empty"):
            engine.submit(Request("d", np.zeros(0, np.int32), 2))

    def test_short_pool_caps_pad_bucket(self):
        """A max_request_len below the prefill bucket must not reject a
        request that actually fits (review regression): prompt 17 +
        3 new = 20 rows in a 24-row bound with chunk 32 used to be
        refused over the uncapped 32-row pad bucket."""
        from kubeshare_tpu.models.decoding import greedy_decode
        from kubeshare_tpu.serving import Request

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        engine = _engine(params, config, num_slots=2, num_blocks=15,
                         max_request_len=24, prefill_chunk=32)
        prompt = np.asarray(jax.random.randint(
            jax.random.PRNGKey(5), (17,), 0, 64), np.int32)
        engine.warmup()
        baseline = engine.compile_counts()
        engine.submit(Request("r0", prompt, 3))
        out = engine.run()["r0"]
        ref = np.asarray(greedy_decode(
            params, config, jnp.asarray(prompt)[None], 3))[0]
        assert out.tokens == list(ref)
        # the capped (non-power-of-two) pad width was part of warmup
        assert engine.compile_counts() == baseline

    def test_eos_retires_early_and_frees_blocks(self):
        from kubeshare_tpu.models.decoding import greedy_decode
        from kubeshare_tpu.serving import Request

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        prompt = np.asarray(jax.random.randint(
            jax.random.PRNGKey(1), (9,), 0, 64), np.int32)
        ref = [int(t) for t in np.asarray(greedy_decode(
            params, config, jnp.asarray(prompt)[None], 8))[0]]
        eos = ref[2]  # the 3rd greedy token becomes "EOS"
        engine = _engine(params, config, eos_token=eos)
        engine.submit(Request("r0", prompt, 8))
        out = engine.run()["r0"]
        # stops AT the stream's first eos occurrence (which may precede
        # index 2 if the token repeats), mid-decode-span included
        assert out.tokens == ref[: ref.index(eos) + 1]
        assert len(out.tokens) < len(ref)
        assert engine.allocator.blocks_in_use == 0

    def test_zero_recompilation_after_warmup(self):
        """The acceptance criterion, asserted via jit cache stats: after
        warmup, a full mixed ragged workload adds ZERO compilations, and
        the prefill widths stay within the O(log chunk) bucket bound."""
        import math

        from kubeshare_tpu.serving import Request

        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        engine = _engine(params, config)
        engine.warmup()
        baseline = engine.compile_counts()
        chunk = engine.engine_config.prefill_chunk
        # widths bucketed to powers of two, lane counts to {1, num_slots}
        assert baseline["prefill"] <= 2 * (int(math.log2(chunk)) + 1)
        assert baseline["decode"] == 1
        rng = np.random.default_rng(5)
        for i in range(8):  # every remainder class over two waves
            engine.submit(Request(
                f"r{i}", rng.integers(0, 64, 2 * chunk + 1 + i),
                int(rng.integers(1, 6))))
        engine.run()
        assert engine.compile_counts() == baseline

    def test_engine_charges_through_guard(self):
        """Fractional-chip integration: every prefill chunk / decode
        step / first-token pick acquires and charges the token guard."""
        from kubeshare_tpu.isolation.guard import ExecutionGuard
        from kubeshare_tpu.serving import EngineConfig, Request, ServingEngine

        class FakeClient:
            def __init__(self):
                self.acquired = 0
                self.released_ms = 0.0

            def acquire(self, estimate_ms):
                self.acquired += 1
                return 1e9  # one grant funds the whole run

            def release(self, used_ms):
                self.released_ms += used_ms

        client = FakeClient()
        guard = ExecutionGuard(client=client, from_env=False,
                               idle_release_ms=0)
        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        engine = ServingEngine(
            params, config,
            EngineConfig(num_slots=2, block_size=4, num_blocks=17,
                         max_request_len=32, prefill_chunk=8),
            guard=guard)
        engine.submit(Request("r0", np.zeros(9, np.int32), 4))
        engine.run()
        assert client.acquired >= 1
        assert guard.total_gated_ms > 0.0
        # run() returned the held token at drain
        assert client.released_ms > 0.0


class TestRaggedPrefill:
    """Satellite: prefill_chunked accepts non-tiling prompts via
    power-of-two bucketed final chunks."""

    def test_matches_bulk_across_remainders(self):
        from kubeshare_tpu.models.decoding import prefill, prefill_chunked

        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        # short-pad, pow2, ragged-with-full-chunks, exact-tile, long-ragged
        for length in (3, 8, 11, 16, 21):
            prompt = jax.random.randint(
                jax.random.PRNGKey(length), (2, length), 0, 64)
            cache_b, logits_b = prefill(params, config, prompt)
            cache_c, logits_c = prefill_chunked(params, config, prompt, 8)
            np.testing.assert_allclose(
                np.asarray(logits_c), np.asarray(logits_b),
                rtol=2e-4, atol=2e-4, err_msg=f"L={length}")
            np.testing.assert_allclose(
                np.asarray(cache_c["k"]), np.asarray(cache_b["k"]),
                rtol=2e-4, atol=2e-4, err_msg=f"L={length}")
            np.testing.assert_allclose(
                np.asarray(cache_c["v"]), np.asarray(cache_b["v"]),
                rtol=2e-4, atol=2e-4, err_msg=f"L={length}")
            assert int(cache_c["length"]) == length

    def test_compile_count_bounded_by_buckets(self):
        """Compile-count regression: across EVERY remainder the chunk
        widths hitting the compiler stay within {chunk} + powers of two
        — O(log chunk) shapes, not one per remainder."""
        import math

        from kubeshare_tpu.models import decoding

        config = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=1, d_ff=64,
            max_seq_len=64, dtype=jnp.float32, attention="reference")
        params = transformer_init(jax.random.PRNGKey(0), config)
        chunk = 8
        widths = set()
        real = decoding._decode_chunk

        def recording(params, config, cache, tokens, *args, **kwargs):
            widths.add(int(tokens.shape[1]))
            return real(params, config, cache, tokens, *args, **kwargs)

        try:
            decoding._decode_chunk = recording
            for length in range(1, 2 * chunk + 1):
                prompt = jnp.zeros((1, length), jnp.int32)
                decoding.prefill_chunked(params, config, prompt, chunk)
        finally:
            decoding._decode_chunk = real
        allowed = {chunk} | {2 ** i for i in range(int(math.log2(chunk)) + 1)}
        assert widths <= allowed, widths
        assert len(widths) <= int(math.log2(chunk)) + 1

    def test_bucket_capped_at_max_seq_len(self):
        """A non-power-of-two max_seq_len below the bucket must not make
        the pad-forward chunk overrun the cache (review regression):
        prompt 17 in a 20-row cache with chunk 32 bucketed to 32 used to
        crash in XLA."""
        from kubeshare_tpu.models.decoding import prefill, prefill_chunked
        from kubeshare_tpu.models.transformer import (
            TransformerConfig, transformer_init)

        config = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=1, d_ff=64,
            max_seq_len=20, dtype=jnp.float32, attention="reference")
        params = transformer_init(jax.random.PRNGKey(0), config)
        prompt = jax.random.randint(jax.random.PRNGKey(4), (1, 17), 0, 64)
        cache_b, logits_b = prefill(params, config, prompt)
        cache_c, logits_c = prefill_chunked(params, config, prompt, 32)
        np.testing.assert_allclose(
            np.asarray(logits_c), np.asarray(logits_b),
            rtol=2e-4, atol=2e-4)
        assert int(cache_c["length"]) == 17

    def test_bucket_width(self):
        from kubeshare_tpu.models.decoding import bucket_width

        assert [bucket_width(r, 8) for r in (1, 2, 3, 4, 5, 7, 8)] == [
            1, 2, 4, 4, 8, 8, 8]
        with pytest.raises(ValueError):
            bucket_width(0, 8)
        with pytest.raises(ValueError):
            bucket_width(9, 8)


class TestServingBenchSmoke:
    def test_smoke_ratio_and_zero_recompiles(self):
        """The bench's CPU smoke path: continuous vs run-to-completion
        on a Poisson mixed-length workload, seconds-fast, recompile-free
        after warmup."""
        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "serving_bench", os.path.join(
                os.path.dirname(__file__), "..", "benchmarks",
                "serving_bench.py"))
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        result = bench.run_bench(bench.smoke_settings())
        assert result["recompiles_after_warmup"] == 0
        assert result["continuous"]["tokens_per_s"] > 0
        assert result["run_to_completion"]["tokens_per_s"] > 0
        # the smoke model is toy-sized and its sub-100ms serve windows
        # jitter with batch-formation timing, so the ratio is noisy
        # (0.5-0.9 observed) and FAR under the full bench's (1.75-2.06x
        # measured — docs/perf.md); this test locks the mechanics and
        # the recompile-free property, not the 1.5x criterion
        assert result["ratio"] > 0.25
