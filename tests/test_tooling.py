"""Simulator, checkpoint, and CLI entry-point tests."""

import json
import os
import subprocess
import sys
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np

from kubeshare_tpu.parallel.checkpoint import (
    latest_checkpoint,
    restore_checkpoint,
    save_checkpoint,
)
from kubeshare_tpu.simulator import parse_trace, run_trace

REPO = os.path.join(os.path.dirname(__file__), "..")
TRACE = os.path.join(REPO, "examples", "trace-small.txt")


class TestSimulator:
    def test_parse_trace(self):
        entries = parse_trace(TRACE)
        assert len(entries) == 60
        assert all(e.chips >= 1 for e in entries)

    def test_run_trace(self):
        report = run_trace(TRACE, nodes=4, chips_per_node=4)
        assert report.submitted == 60
        assert report.bound + report.unschedulable == report.submitted
        assert report.bound > 40  # most of the trace fits a 16-chip cluster
        assert report.completed == report.bound
        assert report.wall_seconds < 30  # virtual clock, not live replay

    def test_run_trace_custom_topology(self):
        # heterogeneous config: inventory must match declared models/counts
        config = os.path.join(REPO, "deploy", "config",
                              "kubeshare-config-v4-cluster.yaml")
        report = run_trace(TRACE, topology_path=config)
        assert report.submitted == 60
        nodes = set(report.placements_per_node)
        assert nodes <= {"tpu-v4-host-a", "tpu-v4-host-b", "tpu-v5e-host-c"}

    def test_cli_simulate(self):
        out = subprocess.run(
            [sys.executable, "-m", "kubeshare_tpu", "simulate",
             "--trace", TRACE, "--nodes", "2", "--chips-per-node", "4"],
            capture_output=True, text=True, cwd=REPO, timeout=120,
        )
        assert out.returncode == 0, out.stderr
        report = json.loads(out.stdout.strip().splitlines()[-1])
        assert report["submitted"] == 60


class TestCheckpoint:
    def test_save_restore_roundtrip(self, tmp_path):
        state = {"w": jnp.arange(6.0).reshape(2, 3), "step": jnp.int32(7)}
        save_checkpoint(str(tmp_path), state, step=7)
        restored = restore_checkpoint(str(tmp_path))
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.asarray(state["w"]))
        assert int(restored["step"]) == 7

    def test_latest_and_gc(self, tmp_path):
        state = {"x": jnp.zeros(2)}
        for step in (1, 5, 3, 9, 12):
            save_checkpoint(str(tmp_path), state, step=step, keep=3)
        step, path = latest_checkpoint(str(tmp_path))
        assert step == 12 and path.endswith("ckpt-12.bin")
        remaining = sorted(os.listdir(tmp_path))
        assert len(remaining) == 3  # keep=3

    def test_restore_trainstate(self, tmp_path):
        from kubeshare_tpu.models import mnist_apply, mnist_init
        from kubeshare_tpu.parallel.train import make_train_step

        init_state, train_step = make_train_step(mnist_apply)
        state = init_state(mnist_init(jax.random.PRNGKey(0)))
        images = jnp.zeros((2, 28, 28, 1))
        labels = jnp.zeros((2,), jnp.int32)
        state, _ = train_step(state, images, labels)
        save_checkpoint(str(tmp_path), state, step=int(state.step))
        restored = restore_checkpoint(str(tmp_path))
        assert int(restored.step) == 1
        # resume training from the restored state
        state2, loss = train_step(restored, images, labels)
        assert int(state2.step) == 2 and np.isfinite(float(loss))


class TestCLI:
    def test_collector_cli(self):
        proc = subprocess.Popen(
            [sys.executable, "-m", "kubeshare_tpu", "collector",
             "--fake-chips", "2", "--port", "0", "--node-name", "cli-node"],
            cwd=REPO, stderr=subprocess.PIPE, text=True,
        )
        try:
            # port 0 is ephemeral; read it from the log line
            line = proc.stderr.readline()
            port = int(line.rsplit(":", 1)[-1].split("/")[0])
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/kubeshare-collector", timeout=5
            ).read().decode()
            assert body.count('node="cli-node"') == 2
        finally:
            proc.kill()
            proc.wait()

    def test_unknown_component(self):
        out = subprocess.run(
            [sys.executable, "-m", "kubeshare_tpu", "nonsense"],
            capture_output=True, text=True, cwd=REPO,
        )
        assert out.returncode != 0


class TestDeployManifests:
    """Construction checks over deploy/ (VERDICT r1 #8): every manifest
    parses, the prometheus-operator ServiceMonitors cover both exporters at
    the reference's 5s cadence (ref deploy/aggregator.yaml:55-58,
    deploy/collector.yaml:27-30), the scheduler-test pod variant exists,
    and every example topology builds a real cell forest."""

    DEPLOY = os.path.join(REPO, "deploy")

    def _load_all(self, name):
        import yaml

        with open(os.path.join(self.DEPLOY, name)) as f:
            return [d for d in yaml.safe_load_all(f) if d]

    def test_all_manifests_parse(self):
        for name in sorted(os.listdir(self.DEPLOY)):
            if name.endswith(".yaml"):
                docs = self._load_all(name)
                assert docs, name
                for doc in docs:
                    assert "kind" in doc and "apiVersion" in doc, name

    def test_servicemonitors_cover_both_exporters(self):
        monitors = {}
        services = {}
        for name in ("aggregator.yaml", "collector.yaml"):
            for doc in self._load_all(name):
                if doc["kind"] == "ServiceMonitor":
                    monitors[doc["metadata"]["name"]] = doc
                if doc["kind"] == "Service":
                    services[doc["metadata"]["name"]] = doc
        assert set(monitors) == {"kubeshare-aggregator", "kubeshare-collector"}
        for name, mon in monitors.items():
            endpoint = mon["spec"]["endpoints"][0]
            assert endpoint["interval"] == "5s"
            assert endpoint["path"] == f"/{name}"
            # the selector actually matches the paired Service's labels
            match = mon["spec"]["selector"]["matchLabels"]
            svc_labels = services[name]["metadata"]["labels"]
            assert all(svc_labels.get(k) == v for k, v in match.items()), name

    def test_scheduler_test_pod_variant(self):
        docs = self._load_all("scheduler-test.yaml")
        assert [d["kind"] for d in docs] == ["Pod"]
        pod = docs[0]
        assert pod["spec"]["restartPolicy"] == "Never"
        command = pod["spec"]["containers"][0]["command"]
        assert "scheduler" in command and "--level=4" in command

    def test_example_topologies_build(self):
        from kubeshare_tpu.cell import (build_cell_chains, build_cell_forest,
                                        load_config)
        from kubeshare_tpu.cell.spec import check_physical_cells

        config_dir = os.path.join(self.DEPLOY, "config")
        names = sorted(os.listdir(config_dir))
        assert len(names) >= 4  # reference ships four examples
        for name in names:
            config = load_config(path=os.path.join(config_dir, name))
            check_physical_cells(config)
            elements, priority, _ = build_cell_chains(config.cell_types)
            forest = build_cell_forest(elements, config.cells)
            assert forest, name
            assert priority, name

    def test_multihost_topology_has_multinode_cell(self):
        """The v4 multihost example must actually exercise multi-node
        cells (ref kubeshare-config-final.yaml:12-27's 2-node cell)."""
        from kubeshare_tpu.cell import build_cell_chains, load_config

        config = load_config(path=os.path.join(
            self.DEPLOY, "config", "kubeshare-config-v4-multihost.yaml"))
        elements, _, _ = build_cell_chains(config.cell_types)
        assert any(e.is_multi_nodes for e in elements.values())

    def test_multislice_topology_marks_slice_level(self):
        """The multislice example must carry the isSliceLevel marker the
        DCN tier and megascale env injection key off, and its two marked
        slices must resolve to distinct slice keys despite the shared
        region root."""
        from kubeshare_tpu.cell import (build_cell_chains, build_cell_forest,
                                        load_config)
        from kubeshare_tpu.cell.topology import slice_key

        config = load_config(path=os.path.join(
            self.DEPLOY, "config", "kubeshare-config-multislice.yaml"))
        slice_types = frozenset(
            name for name, t in config.cell_types.items() if t.is_slice_level)
        assert slice_types == {"TPU-v5e-SLICE"}
        elements, _, _ = build_cell_chains(config.cell_types)
        forest = build_cell_forest(elements, config.cells)
        keys = set()
        for by_level in forest.values():
            for roots in by_level.values():
                for root in roots:
                    for leaf in root.leaves():
                        keys.add(slice_key(leaf, slice_types))
        assert len(keys) == 2  # two ICI domains under one root


class TestExampleWorkloadManifests:
    """Every examples/*.yaml pod manifest must parse AND place through the
    real scheduler — the user-facing files cannot drift from the label
    contract the scenario matrix locks in code."""

    def test_example_pods_schedule(self):
        import yaml

        from kubeshare_tpu import constants
        from kubeshare_tpu.cell import load_config
        from kubeshare_tpu.cell.allocator import ChipInfo
        from kubeshare_tpu.cluster.api import FakeClock, Node, Pod
        from kubeshare_tpu.cluster.fake import FakeCluster
        from kubeshare_tpu.scheduler import (
            KubeShareScheduler, SchedulerEngine, parse_pod_labels)

        examples = os.path.join(REPO, "examples")
        manifests = []
        for name in sorted(os.listdir(examples)):
            if not name.endswith(".yaml"):
                continue
            with open(os.path.join(examples, name)) as f:
                for doc in yaml.safe_load_all(f):
                    if not doc:
                        continue
                    if doc.get("kind") == "Pod":
                        manifests.append((name, doc["metadata"]))
                    elif doc.get("kind") == "Job":
                        # gang examples ship as Jobs: their POD TEMPLATE
                        # carries the sharedgpu labels
                        template = doc["spec"]["template"]
                        manifests.append((name, template["metadata"]))
        assert len(manifests) >= 6  # the acceptance matrix ships as files

        topology = """
cellTypes:
  V5E-NODE:
    childCellType: "TPU-v5e"
    childCellNumber: 8
    childCellPriority: 80
    isNodeLevel: true
cells:
- cellType: V5E-NODE
  cellId: node-a
"""
        inventory = {
            "node-a": [ChipInfo(f"node-a-tpu-{i}", 16 << 30, "TPU-v5e", i)
                       for i in range(8)],
        }
        cluster = FakeCluster()
        cluster.add_node(Node("node-a",
                              {constants.NODE_LABEL_FILTER: "true"}))
        clock = FakeClock(0.0)
        plugin = KubeShareScheduler(
            load_config(text=topology), cluster,
            lambda n: inventory.get(n, []), clock=clock)
        engine = SchedulerEngine(plugin, cluster, clock)
        for i, (name, metadata) in enumerate(manifests):
            labels = {str(k): str(v) for k, v in
                      (metadata.get("labels") or {}).items()}
            status = parse_pod_labels(Pod(name=f"x{i}", labels=labels))
            assert status.limit >= status.request > 0, name
            # schedule enough copies to satisfy any gang barrier; distinct
            # group names per file avoid cross-manifest gang mixing
            copies = status.min_available if status.pod_group else 1
            if status.pod_group:
                labels[constants.POD_GROUP_NAME] = f"g{i}"
            pod_names = [f"{name.replace('.yaml', '')}-{i}-{j}"
                         for j in range(copies)]
            for pod_name in pod_names:
                cluster.create_pod(Pod(
                    name=pod_name, labels=labels,
                    scheduler_name=constants.SCHEDULER_NAME))
            engine.run_until_idle()
            # EVERY copy of THIS manifest must bind (no other manifest's
            # surplus can mask it) ...
            unbound = [n for n in pod_names
                       if not cluster.get_pod("default", n).is_bound()]
            assert not unbound, (name, unbound)
            # ... then reclaim, so each manifest is judged against a full
            # node, not whatever the previous files left over
            for pod_name in pod_names:
                cluster.delete_pod("default", pod_name)


class TestLongContextExample:
    """examples/train_longcontext.py: the round-3 parallelism walkthrough
    must actually train (loss decreases) on the CPU mesh, on both the
    dp x sp (fsdp + zigzag) and 1F1B x sp paths."""

    def _run(self, *extra):
        env = dict(os.environ,
                   JAX_PLATFORMS="cpu",
                   XLA_FLAGS="--xla_force_host_platform_device_count=8")
        out = subprocess.run(
            [sys.executable, "-m", "examples.train_longcontext",
             "--steps", "2", *extra],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=600,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        return out.stdout

    def test_fsdp_zigzag_path(self):
        stdout = self._run()
        assert "zigzag ring" in stdout
        assert "demo complete" in stdout

    def test_1f1b_path(self):
        stdout = self._run("--pp")
        assert "1f1b" in stdout
        # the example asserts loss improvement itself; completion marker
        # proves it got past that check
        assert "demo complete" in stdout


class TestContainerBuildSurface:
    """The packaging surface the reference ships as docker/*/Dockerfile +
    Makefile image targets (ref Makefile:1-20): one image, `make images`,
    and a kind e2e that degrades to a SKIP without a container runtime."""

    def test_dockerfile_copies_what_manifests_expect(self):
        import yaml

        dockerfile = open(os.path.join(REPO, "docker", "Dockerfile")).read()
        # shim artifacts must land where node-daemon.yaml's shim-init copies
        # them from (/opt/tpushare -> /kubeshare/library hostPath)
        assert "/opt/tpushare/" in dockerfile
        assert "libtpushim.so.1" in dockerfile
        assert "libtpushare_client.so" in dockerfile
        # tokend/pmgr on find_binary's search path
        assert "/usr/local/bin" in dockerfile
        assert "tpushare-tokend" in dockerfile and "tpushare-pmgr" in dockerfile
        with open(os.path.join(REPO, "deploy", "node-daemon.yaml")) as fh:
            daemon = list(yaml.safe_load_all(fh))[0]
        init = daemon["spec"]["template"]["spec"]["initContainers"][0]
        assert "/opt/tpushare/libtpushim.so.1" in init["command"][-1]

    def test_make_image_check(self):
        out = subprocess.run(
            ["make", "image-check"], cwd=REPO, capture_output=True, text=True,
            timeout=300,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        assert "image-check: ok" in out.stdout

    def test_make_images_reports_missing_runtime(self):
        """Without docker/podman, `make images` must fail loudly with the
        exact build command — never pretend an image was produced."""
        env = dict(os.environ, DOCKER="")
        out = subprocess.run(
            ["make", "images"], cwd=REPO, env=env, capture_output=True,
            text=True, timeout=300,
        )
        if out.returncode == 0:  # a container runtime exists on this host
            assert "docker build" in out.stdout or "podman" in out.stdout
        else:
            assert "neither docker nor podman found" in out.stderr

    def test_e2e_kind_runs_to_kubectl_boundary(self):
        out = subprocess.run(
            ["sh", os.path.join(REPO, "deploy", "e2e-kind.sh")],
            cwd=REPO, capture_output=True, text=True, timeout=600,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        assert "manifests parse: ok" in out.stdout
        assert "fake-cluster placement: ok" in out.stdout
        # on container-less hosts the script must skip, not fail
        assert ("SKIP" in out.stdout) or ("PASS" in out.stdout)

    def test_vendored_pjrt_header_builds_shim(self):
        header = os.path.join(REPO, "native", "third_party", "xla", "pjrt",
                              "c", "pjrt_c_api.h")
        assert os.path.isfile(header)
        assert "The OpenXLA Authors" in open(header).read()[:200]
