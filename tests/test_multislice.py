"""Multi-slice / DCN awareness (SURVEY §5:462-468, §7.2; VERDICT r4 #4).

A "slice" is one ICI domain: cells under the nearest ``isSliceLevel``-marked
ancestor (or, unmarked, under one root physical cell).  Two behaviors:

- locality scoring charges a flat DCN tier between cells of different
  slices — cross-slice candidates can NEVER beat same-slice ones, even
  when per-slice ICI coordinate systems alias to hop distance 0 (the
  reference's string heuristic, score.go:164-227, had no such tier);
- gangs whose planned layout spans slices get megascale bootstrap env
  (MEGASCALE_NUM_SLICES / SLICE_ID / COORDINATOR_ADDRESS) and per-slice
  TPU_PROCESS_BOUNDS, beside the existing gang env.
"""

from kubeshare_tpu import constants
from kubeshare_tpu.cell import load_config
from kubeshare_tpu.cell.allocator import ChipInfo
from kubeshare_tpu.cluster.api import FakeClock, Node, Pod
from kubeshare_tpu.cluster.fake import FakeCluster
from kubeshare_tpu.scheduler import KubeShareScheduler, SchedulerArgs, SchedulerEngine

from kubeshare_tpu.parallel.distributed import ENV_GANG_NAME, ENV_GANG_SIZE

HBM = 32 << 30

# two 2-host v4 slices; each slice reuses the SAME local ICI coordinate
# system (what a real per-slice runtime reports), so raw hop distance
# aliases across slices
TWO_SLICE_TOPOLOGY = """
cellTypes:
  V4-NODE:
    childCellType: "TPU-v4"
    childCellNumber: 4
    childCellPriority: 60
    isNodeLevel: true
  V4-SLICE:
    childCellType: V4-NODE
    childCellNumber: 2
cells:
- cellType: V4-SLICE
  cellId: slice-a
  cellChildren:
  - cellId: a1
  - cellId: a2
- cellType: V4-SLICE
  cellId: slice-b
  cellChildren:
  - cellId: b1
  - cellId: b2
"""

TWO_SLICE_INVENTORY = {
    # per-slice local coords: host 1 at row 0, host 2 at row 1 — IDENTICAL
    # between the slices, so a1 chip i and b1 chip i alias at distance 0
    "a1": [ChipInfo(f"a1-tpu-{i}", HBM, "TPU-v4", i, (i, 0, 0)) for i in range(4)],
    "a2": [ChipInfo(f"a2-tpu-{i}", HBM, "TPU-v4", i, (i, 1, 0)) for i in range(4)],
    "b1": [ChipInfo(f"b1-tpu-{i}", HBM, "TPU-v4", i, (i, 0, 0)) for i in range(4)],
    "b2": [ChipInfo(f"b2-tpu-{i}", HBM, "TPU-v4", i, (i, 1, 0)) for i in range(4)],
}

# one root grouping two explicitly MARKED slice cells: the marker, not the
# root, must set the DCN boundary
MARKED_SLICE_TOPOLOGY = """
cellTypes:
  V4-NODE:
    childCellType: "TPU-v4"
    childCellNumber: 4
    childCellPriority: 60
    isNodeLevel: true
  V4-SLICE:
    childCellType: V4-NODE
    childCellNumber: 1
    isSliceLevel: true
  V4-REGION:
    childCellType: V4-SLICE
    childCellNumber: 2
cells:
- cellType: V4-REGION
  cellId: region-0
  cellChildren:
  - cellId: s0
    cellChildren:
    - cellId: host-1
  - cellId: s1
    cellChildren:
    - cellId: host-2
"""

MARKED_SLICE_INVENTORY = {
    "host-1": [ChipInfo(f"host-1-tpu-{i}", HBM, "TPU-v4", i, (i, 0, 0)) for i in range(4)],
    "host-2": [ChipInfo(f"host-2-tpu-{i}", HBM, "TPU-v4", i, (i, 0, 0)) for i in range(4)],
}


def gang_pod(name, group, headcount, request="4.0", priority=100):
    return Pod(
        namespace="default",
        name=name,
        labels={
            constants.POD_GPU_REQUEST: request,
            constants.POD_GPU_LIMIT: request,
            constants.POD_PRIORITY: str(priority),
            constants.POD_GROUP_NAME: group,
            constants.POD_GROUP_HEADCOUNT: str(headcount),
            constants.POD_GROUP_THRESHOLD: "1.0",
        },
        scheduler_name=constants.SCHEDULER_NAME,
    )


def make_env(topology, inventory):
    cluster = FakeCluster()
    for node in inventory:
        cluster.add_node(Node(name=node, labels={constants.NODE_LABEL_FILTER: "true"}))
    clock = FakeClock(1000.0)
    plugin = KubeShareScheduler(
        topology=load_config(text=topology),
        cluster=cluster,
        inventory=lambda node: inventory.get(node, []),
        args=SchedulerArgs(),
        clock=clock,
    )
    engine = SchedulerEngine(plugin, cluster, clock)
    return cluster, plugin, engine


def node_slice(plugin, node):
    [leaf] = plugin.allocator.leaf_cells_by_node(node)[:1]
    return plugin.slice_of(leaf)


class TestSliceKey:
    def test_defaults_to_root_cell(self):
        _, plugin, _ = make_env(TWO_SLICE_TOPOLOGY, TWO_SLICE_INVENTORY)
        assert node_slice(plugin, "a1") == node_slice(plugin, "a2") == "slice-a"
        assert node_slice(plugin, "b1") == "slice-b"

    def test_marked_level_overrides_root(self):
        _, plugin, _ = make_env(MARKED_SLICE_TOPOLOGY, MARKED_SLICE_INVENTORY)
        # same root ("region-0") but different marked slice ancestors
        assert node_slice(plugin, "host-1") == "region-0/s0"
        assert node_slice(plugin, "host-2") == "region-0/s1"

    def test_cross_slice_distance_dominates_aliased_coords(self):
        _, plugin, _ = make_env(TWO_SLICE_TOPOLOGY, TWO_SLICE_INVENTORY)
        [a1] = plugin.allocator.leaf_cells_by_node("a1")[:1]
        [a2] = plugin.allocator.leaf_cells_by_node("a2")[:1]
        [b1] = plugin.allocator.leaf_cells_by_node("b1")[:1]
        # b1's chip aliases a1's at ICI distance 0; the DCN tier must
        # still rank it strictly behind any same-slice cell
        assert a1.coords == b1.coords
        assert plugin.cell_distance(a1, b1) >= plugin.DCN_CROSSING_COST
        assert plugin.cell_distance(a1, a2) < plugin.DCN_CROSSING_COST


class TestGangSlicePreference:
    def test_gang_prefers_same_slice_over_aliased_cross_slice(self):
        """A 2-member whole-node gang must co-locate in ONE slice even
        though the sibling slice's identical local coordinates make its
        hosts look ICI-closer (hop distance 0) than the same-slice
        neighbor (hop distance >= 1)."""
        cluster, plugin, engine = make_env(TWO_SLICE_TOPOLOGY, TWO_SLICE_INVENTORY)
        for i in range(2):
            cluster.create_pod(gang_pod(f"w{i}", "ring", 2))
        engine.run_until_idle()
        nodes = [cluster.get_pod("default", f"w{i}").node_name for i in range(2)]
        assert all(nodes)
        slices = {node_slice(plugin, n) for n in nodes}
        assert len(slices) == 1, f"gang spread across slices: {nodes}"
        # same-slice gang: plain gang env, no megascale
        for i in range(2):
            env = cluster.get_pod("default", f"w{i}").containers[0].env
            assert constants.ENV_MEGASCALE_NUM_SLICES not in env
            assert env[constants.ENV_PROCESS_BOUNDS] == "2,1,1"


class TestMegascaleEnv:
    def test_cross_slice_gang_gets_megascale_env(self):
        """A gang that CANNOT fit one slice (2 whole-node members, two
        1-host slices) spans marked slices and every member gets the
        megascale bootstrap beside its gang env."""
        cluster, plugin, engine = make_env(MARKED_SLICE_TOPOLOGY, MARKED_SLICE_INVENTORY)
        for i in range(2):
            cluster.create_pod(gang_pod(f"w{i}", "big", 2))
        engine.run_until_idle()
        slice_ids = set()
        for i in range(2):
            pod = cluster.get_pod("default", f"w{i}")
            assert pod.is_bound()
            env = pod.containers[0].env
            assert env[ENV_GANG_NAME] == "big"
            assert env[ENV_GANG_SIZE] == "2"
            assert env[constants.ENV_MEGASCALE_NUM_SLICES] == "2"
            slice_ids.add(env[constants.ENV_MEGASCALE_SLICE_ID])
            # one member per slice -> per-slice linear grid of 1 process
            assert env[constants.ENV_PROCESS_BOUNDS] == "1,1,1"
            assert env[constants.ENV_CHIPS_PER_PROCESS_BOUNDS] == "4,1,1"
            assert env[constants.ENV_MEGASCALE_COORDINATOR] == (
                f"big-0.big:{constants.MEGASCALE_DEFAULT_PORT}"
            )
            assert env[constants.ENV_MEGASCALE_PORT] == str(
                constants.MEGASCALE_DEFAULT_PORT
            )
        assert slice_ids == {"0", "1"}

    def test_four_member_gang_splits_two_per_slice(self):
        """A 4-member whole-node gang over two 2-host slices must plan
        the uniform 2+2 layout: every member gets per-slice
        TPU_PROCESS_BOUNDS of 2 processes and a slice id shared with
        exactly one peer."""
        cluster, plugin, engine = make_env(TWO_SLICE_TOPOLOGY, TWO_SLICE_INVENTORY)
        for i in range(4):
            cluster.create_pod(gang_pod(f"w{i}", "grid", 4))
        engine.run_until_idle()
        by_slice = {}
        for i in range(4):
            pod = cluster.get_pod("default", f"w{i}")
            assert pod.is_bound()
            env = pod.containers[0].env
            assert env[constants.ENV_MEGASCALE_NUM_SLICES] == "2"
            assert env[constants.ENV_PROCESS_BOUNDS] == "2,1,1"
            assert env[constants.ENV_CHIPS_PER_PROCESS_BOUNDS] == "4,1,1"
            by_slice.setdefault(
                env[constants.ENV_MEGASCALE_SLICE_ID], []).append(i)
        assert sorted(len(v) for v in by_slice.values()) == [2, 2]
        # placement agrees with the bootstrap: same slice id -> same
        # physical slice
        for members in by_slice.values():
            slices = {node_slice(
                plugin, cluster.get_pod("default", f"w{i}").node_name)
                for i in members}
            assert len(slices) == 1

    def test_uneven_capacity_degrades_to_linear_gang_grid(self):
        """libtpu multi-slice needs identically-shaped slices.  A gang of
        3 whole-node members over a 2-host slice + 1-host slice has no
        uniform layout, so NO member may get megascale env — everyone
        keeps the gang-wide linear process grid."""
        inventory = {
            "a1": TWO_SLICE_INVENTORY["a1"],
            "a2": TWO_SLICE_INVENTORY["a2"],
            "b1": TWO_SLICE_INVENTORY["b1"],
        }
        topology = """
cellTypes:
  V4-NODE:
    childCellType: "TPU-v4"
    childCellNumber: 4
    childCellPriority: 60
    isNodeLevel: true
  V4-SLICE:
    childCellType: V4-NODE
    childCellNumber: 2
  V4-SLICE-1:
    childCellType: V4-NODE
    childCellNumber: 1
cells:
- cellType: V4-SLICE
  cellId: slice-a
  cellChildren:
  - cellId: a1
  - cellId: a2
- cellType: V4-SLICE-1
  cellId: slice-b
  cellChildren:
  - cellId: b1
"""
        cluster, plugin, engine = make_env(topology, inventory)
        for i in range(3):
            cluster.create_pod(gang_pod(f"w{i}", "odd", 3))
        engine.run_until_idle()
        for i in range(3):
            pod = cluster.get_pod("default", f"w{i}")
            assert pod.is_bound()
            env = pod.containers[0].env
            assert constants.ENV_MEGASCALE_NUM_SLICES not in env
            assert constants.ENV_MEGASCALE_SLICE_ID not in env
            assert env[constants.ENV_PROCESS_BOUNDS] == "3,1,1"

    def test_single_slice_gang_gets_no_megascale_env(self):
        cluster, plugin, engine = make_env(TWO_SLICE_TOPOLOGY, TWO_SLICE_INVENTORY)
        for i in range(2):
            cluster.create_pod(
                gang_pod(f"w{i}", "small", 2, request="0.5", priority=0)
            )
        engine.run_until_idle()
        for i in range(2):
            pod = cluster.get_pod("default", f"w{i}")
            assert pod.is_bound()
            env = pod.containers[0].env
            assert constants.ENV_MEGASCALE_NUM_SLICES not in env
            assert constants.ENV_MEGASCALE_SLICE_ID not in env


class TestMegascaleBootstrapDrive:
    """The injected MEGASCALE env consumed end-to-end (ROADMAP r5 #3):
    the scheduler places a cross-slice gang, then two OS processes
    carrying each bound pod's ACTUAL container env build the DCN-outer
    mesh the env describes and agree on a psum across the slice axis —
    the single-slice analogue of this chain is
    test_scheduler.test_gang_env_drives_distributed_workload."""

    def test_megascale_env_drives_cross_slice_psum(self, tmp_path):
        import os
        import subprocess
        import sys

        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from native_helpers import free_port

        cluster, plugin, engine = make_env(
            MARKED_SLICE_TOPOLOGY, MARKED_SLICE_INVENTORY
        )
        for i in range(2):
            cluster.create_pod(gang_pod(f"w{i}", "big", 2))
        engine.run_until_idle()
        assert all(
            cluster.get_pod("default", f"w{i}").is_bound() for i in range(2)
        )

        port = free_port()
        worker = tmp_path / "megascale_worker.py"
        worker.write_text(
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "import numpy as np\n"
            "from jax.sharding import PartitionSpec as P\n"
            "from jax.experimental import multihost_utils\n"
            "from kubeshare_tpu.parallel.distributed import (\n"
            "    initialize_from_env, multislice_spec_from_env,\n"
            "    slice_device_mesh)\n"
            "ms = multislice_spec_from_env()\n"
            "assert ms is not None and ms.num_slices == 2, ms\n"
            "assert ms.processes_per_slice == 1, ms\n"
            "spec = initialize_from_env()\n"
            "assert spec is not None and spec.num_processes == 2\n"
            "mesh = slice_device_mesh(ms)\n"
            "assert mesh.devices.shape == (2, 1), mesh.devices.shape\n"
            "# my device must land in MY slice's row of the mesh\n"
            "assert (mesh.devices[ms.slice_id, 0].process_index\n"
            "        == jax.process_index())\n"
            "f = jax.jit(jax.shard_map(\n"
            "    lambda x: jax.lax.psum(x, 'dcn'), mesh=mesh,\n"
            "    in_specs=P('dcn'), out_specs=P()))\n"
            "x = multihost_utils.host_local_array_to_global_array(\n"
            "    np.full((1,), float(ms.slice_id + 1)), mesh, P('dcn'))\n"
            "total = float(f(x).addressable_data(0)[0])\n"
            "# 1 (slice 0) + 2 (slice 1): both DCN rows contributed\n"
            "assert total == 3.0, total\n"
            "print(f'slice {ms.slice_id} dcn_psum_ok {total}')\n"
        )

        procs = []
        try:
            for i in range(2):
                injected = cluster.get_pod("default", f"w{i}").containers[0].env
                assert injected[constants.ENV_MEGASCALE_NUM_SLICES] == "2"
                env = dict(os.environ)
                env.update(injected)
                env["TPUSHARE_COORDINATOR"] = f"127.0.0.1:{port}"
                env["JAX_PLATFORMS"] = "cpu"
                env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
                env["PYTHONPATH"] = os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__))
                )
                env.pop("LD_PRELOAD", None)
                procs.append(subprocess.Popen(
                    [sys.executable, str(worker)], env=env,
                    stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                    text=True,
                ))
            outs = [p.communicate(timeout=180) for p in procs]
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait()
        slices_seen = set()
        for p, (out, err) in zip(procs, outs):
            assert p.returncode == 0, f"{out}\n{err}"
            [marker] = [ln for ln in out.splitlines() if "dcn_psum_ok" in ln]
            slices_seen.add(marker.split()[1])
        assert slices_seen == {"0", "1"}


class TestMultisliceSpecGuards:
    def test_num_slices_without_slice_id_is_rejected(self):
        from kubeshare_tpu.parallel.distributed import multislice_spec_from_env

        # the plugin injects the pair together; a count with no id must
        # read as "broken contract", not "slice 0"
        assert multislice_spec_from_env(
            {constants.ENV_MEGASCALE_NUM_SLICES: "2"}) is None
        assert multislice_spec_from_env(
            {constants.ENV_MEGASCALE_NUM_SLICES: "2",
             constants.ENV_MEGASCALE_SLICE_ID: "junk"}) is None
        spec = multislice_spec_from_env(
            {constants.ENV_MEGASCALE_NUM_SLICES: "2",
             constants.ENV_MEGASCALE_SLICE_ID: "1",
             constants.ENV_PROCESS_BOUNDS: "2,1,1"})
        assert spec is not None
        assert (spec.num_slices, spec.slice_id, spec.processes_per_slice) \
            == (2, 1, 2)

    def test_uneven_device_grouping_is_rejected(self, monkeypatch):
        import jax
        import pytest

        import kubeshare_tpu.parallel.distributed as dist

        class FakeDev:
            # slice_index stamps partitioning into num_slices groups ->
            # hardware path, no allgather
            def __init__(self, i, s):
                self.id = i
                self.process_index = 0
                self.slice_index = s

        devs = [FakeDev(0, 0), FakeDev(1, 0), FakeDev(2, 0), FakeDev(3, 1)]
        # slice_device_mesh imports jax function-locally, so patch the
        # real module's devices(), not a dist-level attribute
        monkeypatch.setattr(jax, "devices", lambda *a, **k: devs)
        ms = dist.MultisliceSpec(num_slices=2, slice_id=0,
                                 processes_per_slice=1)
        with pytest.raises(ValueError, match="unevenly"):
            # 3+1 grouping tiles 4 % 2 == 0 but must still be rejected
            dist.slice_device_mesh(ms)

    def test_hardware_slice_stamps_build_the_mesh(self, monkeypatch):
        """When slice_index partitions cleanly the mesh groups by it,
        with no cross-process gather."""
        import jax
        import pytest

        import kubeshare_tpu.parallel.distributed as dist

        class FakeDev:
            def __init__(self, i, s):
                self.id = i
                self.process_index = i % 2
                self.slice_index = s

        devs = [FakeDev(0, 1), FakeDev(1, 0), FakeDev(2, 1), FakeDev(3, 0)]
        monkeypatch.setattr(jax, "devices", lambda *a, **k: devs)
        # any allgather attempt means the hardware path was NOT taken
        import jax.experimental.multihost_utils as mh
        monkeypatch.setattr(
            mh, "process_allgather",
            lambda *a, **k: pytest.fail("allgather on the hardware path"))
        ms = dist.MultisliceSpec(num_slices=2, slice_id=0,
                                 processes_per_slice=2)
        mesh = dist.slice_device_mesh(ms)
        assert mesh.devices.shape == (2, 2)
        assert [[d.slice_index for d in row]
                for row in mesh.devices] == [[0, 0], [1, 1]]
