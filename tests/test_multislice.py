"""Multi-slice / DCN awareness (SURVEY §5:462-468, §7.2; VERDICT r4 #4).

A "slice" is one ICI domain: cells under the nearest ``isSliceLevel``-marked
ancestor (or, unmarked, under one root physical cell).  Two behaviors:

- locality scoring charges a flat DCN tier between cells of different
  slices — cross-slice candidates can NEVER beat same-slice ones, even
  when per-slice ICI coordinate systems alias to hop distance 0 (the
  reference's string heuristic, score.go:164-227, had no such tier);
- gangs whose planned layout spans slices get megascale bootstrap env
  (MEGASCALE_NUM_SLICES / SLICE_ID / COORDINATOR_ADDRESS) and per-slice
  TPU_PROCESS_BOUNDS, beside the existing gang env.
"""

from kubeshare_tpu import constants
from kubeshare_tpu.cell import load_config
from kubeshare_tpu.cell.allocator import ChipInfo
from kubeshare_tpu.cluster.api import FakeClock, Node, Pod
from kubeshare_tpu.cluster.fake import FakeCluster
from kubeshare_tpu.scheduler import KubeShareScheduler, SchedulerArgs, SchedulerEngine

from kubeshare_tpu.parallel.distributed import ENV_GANG_NAME, ENV_GANG_SIZE

HBM = 32 << 30

# two 2-host v4 slices; each slice reuses the SAME local ICI coordinate
# system (what a real per-slice runtime reports), so raw hop distance
# aliases across slices
TWO_SLICE_TOPOLOGY = """
cellTypes:
  V4-NODE:
    childCellType: "TPU-v4"
    childCellNumber: 4
    childCellPriority: 60
    isNodeLevel: true
  V4-SLICE:
    childCellType: V4-NODE
    childCellNumber: 2
cells:
- cellType: V4-SLICE
  cellId: slice-a
  cellChildren:
  - cellId: a1
  - cellId: a2
- cellType: V4-SLICE
  cellId: slice-b
  cellChildren:
  - cellId: b1
  - cellId: b2
"""

TWO_SLICE_INVENTORY = {
    # per-slice local coords: host 1 at row 0, host 2 at row 1 — IDENTICAL
    # between the slices, so a1 chip i and b1 chip i alias at distance 0
    "a1": [ChipInfo(f"a1-tpu-{i}", HBM, "TPU-v4", i, (i, 0, 0)) for i in range(4)],
    "a2": [ChipInfo(f"a2-tpu-{i}", HBM, "TPU-v4", i, (i, 1, 0)) for i in range(4)],
    "b1": [ChipInfo(f"b1-tpu-{i}", HBM, "TPU-v4", i, (i, 0, 0)) for i in range(4)],
    "b2": [ChipInfo(f"b2-tpu-{i}", HBM, "TPU-v4", i, (i, 1, 0)) for i in range(4)],
}

# one root grouping two explicitly MARKED slice cells: the marker, not the
# root, must set the DCN boundary
MARKED_SLICE_TOPOLOGY = """
cellTypes:
  V4-NODE:
    childCellType: "TPU-v4"
    childCellNumber: 4
    childCellPriority: 60
    isNodeLevel: true
  V4-SLICE:
    childCellType: V4-NODE
    childCellNumber: 1
    isSliceLevel: true
  V4-REGION:
    childCellType: V4-SLICE
    childCellNumber: 2
cells:
- cellType: V4-REGION
  cellId: region-0
  cellChildren:
  - cellId: s0
    cellChildren:
    - cellId: host-1
  - cellId: s1
    cellChildren:
    - cellId: host-2
"""

MARKED_SLICE_INVENTORY = {
    "host-1": [ChipInfo(f"host-1-tpu-{i}", HBM, "TPU-v4", i, (i, 0, 0)) for i in range(4)],
    "host-2": [ChipInfo(f"host-2-tpu-{i}", HBM, "TPU-v4", i, (i, 0, 0)) for i in range(4)],
}


def gang_pod(name, group, headcount, request="4.0", priority=100):
    return Pod(
        namespace="default",
        name=name,
        labels={
            constants.POD_GPU_REQUEST: request,
            constants.POD_GPU_LIMIT: request,
            constants.POD_PRIORITY: str(priority),
            constants.POD_GROUP_NAME: group,
            constants.POD_GROUP_HEADCOUNT: str(headcount),
            constants.POD_GROUP_THRESHOLD: "1.0",
        },
        scheduler_name=constants.SCHEDULER_NAME,
    )


def make_env(topology, inventory):
    cluster = FakeCluster()
    for node in inventory:
        cluster.add_node(Node(name=node, labels={constants.NODE_LABEL_FILTER: "true"}))
    clock = FakeClock(1000.0)
    plugin = KubeShareScheduler(
        topology=load_config(text=topology),
        cluster=cluster,
        inventory=lambda node: inventory.get(node, []),
        args=SchedulerArgs(),
        clock=clock,
    )
    engine = SchedulerEngine(plugin, cluster, clock)
    return cluster, plugin, engine


def node_slice(plugin, node):
    [leaf] = plugin.allocator.leaf_cells_by_node(node)[:1]
    return plugin.slice_of(leaf)


class TestSliceKey:
    def test_defaults_to_root_cell(self):
        _, plugin, _ = make_env(TWO_SLICE_TOPOLOGY, TWO_SLICE_INVENTORY)
        assert node_slice(plugin, "a1") == node_slice(plugin, "a2") == "slice-a"
        assert node_slice(plugin, "b1") == "slice-b"

    def test_marked_level_overrides_root(self):
        _, plugin, _ = make_env(MARKED_SLICE_TOPOLOGY, MARKED_SLICE_INVENTORY)
        # same root ("region-0") but different marked slice ancestors
        assert node_slice(plugin, "host-1") == "region-0/s0"
        assert node_slice(plugin, "host-2") == "region-0/s1"

    def test_cross_slice_distance_dominates_aliased_coords(self):
        _, plugin, _ = make_env(TWO_SLICE_TOPOLOGY, TWO_SLICE_INVENTORY)
        [a1] = plugin.allocator.leaf_cells_by_node("a1")[:1]
        [a2] = plugin.allocator.leaf_cells_by_node("a2")[:1]
        [b1] = plugin.allocator.leaf_cells_by_node("b1")[:1]
        # b1's chip aliases a1's at ICI distance 0; the DCN tier must
        # still rank it strictly behind any same-slice cell
        assert a1.coords == b1.coords
        assert plugin.cell_distance(a1, b1) >= plugin.DCN_CROSSING_COST
        assert plugin.cell_distance(a1, a2) < plugin.DCN_CROSSING_COST


class TestGangSlicePreference:
    def test_gang_prefers_same_slice_over_aliased_cross_slice(self):
        """A 2-member whole-node gang must co-locate in ONE slice even
        though the sibling slice's identical local coordinates make its
        hosts look ICI-closer (hop distance 0) than the same-slice
        neighbor (hop distance >= 1)."""
        cluster, plugin, engine = make_env(TWO_SLICE_TOPOLOGY, TWO_SLICE_INVENTORY)
        for i in range(2):
            cluster.create_pod(gang_pod(f"w{i}", "ring", 2))
        engine.run_until_idle()
        nodes = [cluster.get_pod("default", f"w{i}").node_name for i in range(2)]
        assert all(nodes)
        slices = {node_slice(plugin, n) for n in nodes}
        assert len(slices) == 1, f"gang spread across slices: {nodes}"
        # same-slice gang: plain gang env, no megascale
        for i in range(2):
            env = cluster.get_pod("default", f"w{i}").containers[0].env
            assert constants.ENV_MEGASCALE_NUM_SLICES not in env
            assert env[constants.ENV_PROCESS_BOUNDS] == "2,1,1"


class TestMegascaleEnv:
    def test_cross_slice_gang_gets_megascale_env(self):
        """A gang that CANNOT fit one slice (2 whole-node members, two
        1-host slices) spans marked slices and every member gets the
        megascale bootstrap beside its gang env."""
        cluster, plugin, engine = make_env(MARKED_SLICE_TOPOLOGY, MARKED_SLICE_INVENTORY)
        for i in range(2):
            cluster.create_pod(gang_pod(f"w{i}", "big", 2))
        engine.run_until_idle()
        slice_ids = set()
        for i in range(2):
            pod = cluster.get_pod("default", f"w{i}")
            assert pod.is_bound()
            env = pod.containers[0].env
            assert env[ENV_GANG_NAME] == "big"
            assert env[ENV_GANG_SIZE] == "2"
            assert env[constants.ENV_MEGASCALE_NUM_SLICES] == "2"
            slice_ids.add(env[constants.ENV_MEGASCALE_SLICE_ID])
            # one member per slice -> per-slice linear grid of 1 process
            assert env[constants.ENV_PROCESS_BOUNDS] == "1,1,1"
            assert env[constants.ENV_CHIPS_PER_PROCESS_BOUNDS] == "4,1,1"
            assert env[constants.ENV_MEGASCALE_COORDINATOR] == (
                f"big-0.big:{constants.MEGASCALE_DEFAULT_PORT}"
            )
            assert env[constants.ENV_MEGASCALE_PORT] == str(
                constants.MEGASCALE_DEFAULT_PORT
            )
        assert slice_ids == {"0", "1"}

    def test_four_member_gang_splits_two_per_slice(self):
        """A 4-member whole-node gang over two 2-host slices must plan
        the uniform 2+2 layout: every member gets per-slice
        TPU_PROCESS_BOUNDS of 2 processes and a slice id shared with
        exactly one peer."""
        cluster, plugin, engine = make_env(TWO_SLICE_TOPOLOGY, TWO_SLICE_INVENTORY)
        for i in range(4):
            cluster.create_pod(gang_pod(f"w{i}", "grid", 4))
        engine.run_until_idle()
        by_slice = {}
        for i in range(4):
            pod = cluster.get_pod("default", f"w{i}")
            assert pod.is_bound()
            env = pod.containers[0].env
            assert env[constants.ENV_MEGASCALE_NUM_SLICES] == "2"
            assert env[constants.ENV_PROCESS_BOUNDS] == "2,1,1"
            assert env[constants.ENV_CHIPS_PER_PROCESS_BOUNDS] == "4,1,1"
            by_slice.setdefault(
                env[constants.ENV_MEGASCALE_SLICE_ID], []).append(i)
        assert sorted(len(v) for v in by_slice.values()) == [2, 2]
        # placement agrees with the bootstrap: same slice id -> same
        # physical slice
        for members in by_slice.values():
            slices = {node_slice(
                plugin, cluster.get_pod("default", f"w{i}").node_name)
                for i in members}
            assert len(slices) == 1

    def test_uneven_capacity_degrades_to_linear_gang_grid(self):
        """libtpu multi-slice needs identically-shaped slices.  A gang of
        3 whole-node members over a 2-host slice + 1-host slice has no
        uniform layout, so NO member may get megascale env — everyone
        keeps the gang-wide linear process grid."""
        inventory = {
            "a1": TWO_SLICE_INVENTORY["a1"],
            "a2": TWO_SLICE_INVENTORY["a2"],
            "b1": TWO_SLICE_INVENTORY["b1"],
        }
        topology = """
cellTypes:
  V4-NODE:
    childCellType: "TPU-v4"
    childCellNumber: 4
    childCellPriority: 60
    isNodeLevel: true
  V4-SLICE:
    childCellType: V4-NODE
    childCellNumber: 2
  V4-SLICE-1:
    childCellType: V4-NODE
    childCellNumber: 1
cells:
- cellType: V4-SLICE
  cellId: slice-a
  cellChildren:
  - cellId: a1
  - cellId: a2
- cellType: V4-SLICE-1
  cellId: slice-b
  cellChildren:
  - cellId: b1
"""
        cluster, plugin, engine = make_env(topology, inventory)
        for i in range(3):
            cluster.create_pod(gang_pod(f"w{i}", "odd", 3))
        engine.run_until_idle()
        for i in range(3):
            pod = cluster.get_pod("default", f"w{i}")
            assert pod.is_bound()
            env = pod.containers[0].env
            assert constants.ENV_MEGASCALE_NUM_SLICES not in env
            assert constants.ENV_MEGASCALE_SLICE_ID not in env
            assert env[constants.ENV_PROCESS_BOUNDS] == "3,1,1"

    def test_single_slice_gang_gets_no_megascale_env(self):
        cluster, plugin, engine = make_env(TWO_SLICE_TOPOLOGY, TWO_SLICE_INVENTORY)
        for i in range(2):
            cluster.create_pod(
                gang_pod(f"w{i}", "small", 2, request="0.5", priority=0)
            )
        engine.run_until_idle()
        for i in range(2):
            pod = cluster.get_pod("default", f"w{i}")
            assert pod.is_bound()
            env = pod.containers[0].env
            assert constants.ENV_MEGASCALE_NUM_SLICES not in env
            assert constants.ENV_MEGASCALE_SLICE_ID not in env
