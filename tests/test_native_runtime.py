"""Native token-runtime tests: real tpushare-tokend / tpushare-pmgr binaries
over TCP, the Python + ctypes clients, the supervisor, and share enforcement."""

import os
import socket
import subprocess
import threading
import time

import pytest

from kubeshare_tpu.isolation import ExecutionGuard, NativeTokenClient, TokenClient
from kubeshare_tpu.isolation.guard import apply_hbm_cap
from kubeshare_tpu.runtime import ChipSupervisor, find_binary
from kubeshare_tpu.utils.atomicfile import write_atomic

from native_helpers import free_port, wait_listening

TOKEND = find_binary("tpushare-tokend")
PMGR = find_binary("tpushare-pmgr")

pytestmark = pytest.mark.skipif(
    TOKEND is None or PMGR is None, reason="native binaries not built"
)


def _start_tokend(tmp_path, exclusive=False, config=None):
    config_dir = tmp_path / "config"
    config_dir.mkdir(exist_ok=True)
    uuid = "chip-0"
    write_atomic(
        str(config_dir / uuid),
        config or "2\nns/pod-a 1.0 0.5 1000000\nns/pod-b 1.0 0.3 500000\n",
    )
    port = free_port()
    cmd = [TOKEND, "-p", str(config_dir), "-f", uuid, "-P", str(port),
           "-q", "50", "-m", "5", "-w", "1000"]
    if exclusive:
        cmd.append("-x")
    proc = subprocess.Popen(cmd, stderr=subprocess.DEVNULL)
    wait_listening(port)
    return proc, {"port": port, "config_dir": config_dir, "uuid": uuid}


@pytest.fixture
def tokend(tmp_path):
    """Concurrent-mode (default) tokend, two pods at 0.5/0.3."""
    proc, info = _start_tokend(tmp_path)
    yield info
    proc.kill()
    proc.wait()


@pytest.fixture
def tokend_exclusive(tmp_path):
    """Exclusive-mode (-x, Gemini-parity) tokend."""
    proc, info = _start_tokend(tmp_path, exclusive=True)
    yield info
    proc.kill()
    proc.wait()


class TestTokend:
    def test_acquire_release(self, tokend):
        client = TokenClient("127.0.0.1", tokend["port"], "ns/pod-a")
        quota = client.acquire()
        assert quota > 0
        client.release(5.0)
        assert '"ns/pod-a"' in client.stat()
        client.close()

    def test_exclusive_token(self, tokend_exclusive):
        a = TokenClient("127.0.0.1", tokend_exclusive["port"], "ns/pod-a")
        b = TokenClient("127.0.0.1", tokend_exclusive["port"], "ns/pod-b")
        a.acquire()
        granted = []

        def try_b():
            b.acquire()
            granted.append(time.monotonic())
            b.release(1.0)

        t = threading.Thread(target=try_b)
        t.start()
        time.sleep(0.2)
        assert not granted  # b blocked while a holds the token
        a.release(1.0)
        t.join(timeout=5)
        assert granted
        a.close(); b.close()

    def test_multi_grant_disconnect_abandons_all(self, tokend):
        """ADVICE r1: one connection acquiring several tokens (or tokens
        for two pod names) then dying must abandon every grant — a stale
        holders_ entry would wedge exclusive-mode grants forever."""
        import json

        s = socket.create_connection(("127.0.0.1", tokend["port"]))
        for req in (b"REQ ns/pod-a 1.0\n", b"REQ ns/pod-a 1.0\n",
                    b"REQ ns/pod-b 1.0\n"):
            s.sendall(req)
            reply = b""
            while not reply.endswith(b"\n"):
                reply += s.recv(1)
            assert reply.startswith(b"TOK ")
        probe = TokenClient("127.0.0.1", tokend["port"], "x")
        assert json.loads(probe.stat())["holders"] == 3  # a(x2) + b
        s.close()  # die holding three grants
        deadline = time.time() + 5
        holders = None
        while time.time() < deadline:
            holders = json.loads(probe.stat())["holders"]
            if holders == 0:
                break
            time.sleep(0.05)
        probe.close()
        assert holders == 0

    def test_blocking_acquire_grants_immediately_when_free(self, tokend):
        # raw REQB against a free chip answers TOK without parking
        s = socket.create_connection(("127.0.0.1", tokend["port"]))
        s.sendall(b"REQB ns/pod-a 1.0 2000\n")
        reply = b""
        while not reply.endswith(b"\n"):
            reply += s.recv(1)
        assert reply.startswith(b"TOK ")
        s.close()

    def test_blocking_acquire_parks_until_timeout(self, tokend_exclusive):
        """REQB with a busy chip parks server-side and answers WAIT only
        after the requested timeout — the long-poll contract (the client
        then simply re-issues; no 5 ms poll storm)."""
        a = TokenClient("127.0.0.1", tokend_exclusive["port"], "ns/pod-a")
        a.acquire()
        try:
            s = socket.create_connection(
                ("127.0.0.1", tokend_exclusive["port"]))
            start = time.monotonic()
            s.sendall(b"REQB ns/pod-b 1.0 400\n")
            reply = b""
            while not reply.endswith(b"\n"):
                reply += s.recv(1)
            elapsed = time.monotonic() - start
            assert reply.startswith(b"WAIT ")
            assert elapsed >= 0.3, f"REQB returned early ({elapsed:.3f}s)"
            s.close()
        finally:
            a.release(1.0)
            a.close()

    def test_blocking_acquire_wakes_on_release(self, tokend_exclusive):
        """The release must WAKE a parked REQB immediately (event-driven
        handoff), not at a poll tick: measured end-to-end latency from
        release to grant stays far under the 2 s park window."""
        a = TokenClient("127.0.0.1", tokend_exclusive["port"], "ns/pod-a")
        b = TokenClient("127.0.0.1", tokend_exclusive["port"], "ns/pod-b")
        a.acquire()
        granted_at = []

        def wait_b():
            b.acquire()
            granted_at.append(time.monotonic())
            b.release(1.0)

        t = threading.Thread(target=wait_b)
        t.start()
        time.sleep(0.3)  # b is parked server-side by now
        released_at = time.monotonic()
        a.release(1.0)
        t.join(timeout=5)
        assert granted_at, "parked REQB never granted after release"
        assert granted_at[0] - released_at < 0.2, (
            f"handoff took {granted_at[0] - released_at:.3f}s — not "
            f"event-driven")
        a.close(); b.close()

    def test_client_falls_back_to_req_on_old_daemon(self):
        """A TokenClient against a daemon that answers ERR for REQB must
        degrade to REQ polling transparently."""
        server = socket.socket()
        server.bind(("127.0.0.1", 0))
        server.listen(1)
        port = server.getsockname()[1]
        replies = []

        def serve():
            conn, _ = server.accept()
            f = conn.makefile("rw", newline="\n")
            for line in f:
                replies.append(line.strip())
                if line.startswith("REQB"):
                    f.write("ERR unknown command\n")
                elif line.startswith("REQ"):
                    f.write("TOK 100\n")
                f.flush()

        t = threading.Thread(target=serve, daemon=True)
        t.start()
        client = TokenClient("127.0.0.1", port, "ns/pod-a")
        assert client.acquire() == 100.0
        assert any(r.startswith("REQB") for r in replies)
        assert any(r.startswith("REQ ") for r in replies)
        # the fallback is sticky: the next acquire goes straight to REQ
        assert client.acquire() == 100.0
        assert sum(1 for r in replies if r.startswith("REQB")) == 1
        client.close()
        server.close()

    def test_exclusive_reqb_contention_progresses(self, tmp_path):
        """Lost-wakeup stress for the REQB park/notify path: several
        clients fighting over an exclusive chip must all keep making
        progress — a missed notify would strand a parked waiter until
        its 2s window expires (visible as a collapsed grant count)."""
        proc, info = _start_tokend(
            tmp_path,
            config=("4\nns/p0 1.0 0.25 0\nns/p1 1.0 0.25 0\n"
                    "ns/p2 1.0 0.25 0\nns/p3 1.0 0.25 0\n"),
            exclusive=True)
        try:
            counts = {}
            lock = threading.Lock()

            def worker(pod):
                client = TokenClient("127.0.0.1", info["port"], pod)
                done = 0
                stop = time.monotonic() + 2.0
                while time.monotonic() < stop:
                    client.acquire()
                    client.release(0.5)
                    done += 1
                with lock:
                    counts[pod] = done
                client.close()

            threads = [threading.Thread(target=worker, args=(f"ns/p{i}",))
                       for i in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=15)
            assert all(not t.is_alive() for t in threads), counts
            # every worker finished NORMALLY (a crashed worker never
            # writes its count — an empty/partial dict must fail, not
            # pass vacuously) and made real progress (a stranded waiter
            # would show single-digit counts from repeated park expiries)
            assert sorted(counts) == [f"ns/p{i}" for i in range(4)], counts
            assert all(c >= 50 for c in counts.values()), counts
        finally:
            proc.kill()
            proc.wait()

    def test_client_honors_hint_from_poll_shaped_server(self):
        """A WAIT answered well before the park window (old daemon or the
        -G gang gate, which degrades REQB to poll-shaped) must make the
        client sleep the retry hint — NOT re-issue REQB in a tight loop
        (code-review r5: busy-spin burned the serial host core)."""
        server = socket.socket()
        server.bind(("127.0.0.1", 0))
        server.listen(1)
        port = server.getsockname()[1]
        seen = []

        def serve():
            conn, _ = server.accept()
            f = conn.makefile("rw", newline="\n")
            for line in f:
                seen.append((time.monotonic(), line.strip()))
                if len(seen) >= 4:
                    f.write("TOK 100\n")
                else:
                    f.write("WAIT 50\n")  # immediate, poll-shaped
                f.flush()

        t = threading.Thread(target=serve, daemon=True)
        t.start()
        client = TokenClient("127.0.0.1", port, "ns/pod-a")
        assert client.acquire() == 100.0
        # 3 WAITs at a 50ms hint: the acquire must have taken >= ~150ms
        # (a busy-spin finishes in ~1ms and sends hundreds of requests)
        assert len(seen) == 4
        assert seen[-1][0] - seen[0][0] >= 0.12
        client.close()
        server.close()

    def test_concurrent_holders(self, tokend):
        # default mode: both pods may hold tokens simultaneously
        a = TokenClient("127.0.0.1", tokend["port"], "ns/pod-a")
        b = TokenClient("127.0.0.1", tokend["port"], "ns/pod-b")
        assert a.acquire() > 0
        assert b.acquire() > 0  # does not block
        import json

        stat = json.loads(a.stat())
        assert stat["mode"] == "concurrent" and stat["holders"] == 2
        a.release(1.0); b.release(1.0)
        a.close(); b.close()

    def test_limit_cap_throttles(self, tmp_path):
        # pod capped at limit 0.2 of a 1000ms window; charging 100ms per
        # token must throttle grant rate to ~2 per window
        proc, info = _start_tokend(tmp_path, config="1\nns/greedy 0.2 0.1 0\n")
        try:
            client = TokenClient("127.0.0.1", info["port"], "ns/greedy")
            grants = 0
            start = time.monotonic()
            while time.monotonic() - start < 1.5:
                client.acquire()
                client.release(100.0)  # claims 100ms device time per token
                grants += 1
            client.close()
            # uncapped this loop does hundreds of grants; the 0.2 limit
            # allows roughly 0.2*1000ms/100ms = 2 per window plus decay slack
            assert grants <= 8, grants
        finally:
            proc.kill()
            proc.wait()

    def test_memory_cap(self, tokend):
        client = TokenClient("127.0.0.1", tokend["port"], "ns/pod-b")
        ok, used, cap = client.request_memory(400000)
        assert ok and used == 400000 and cap == 500000
        ok, used, cap = client.request_memory(200000)
        assert not ok and used == 400000  # 600000 > cap
        ok, _, _ = client.request_memory(-400000)
        assert ok
        client.close()

    def test_dropped_holder_recovers(self, tokend):
        a = TokenClient("127.0.0.1", tokend["port"], "ns/pod-a")
        a.acquire()
        a.close()  # dies holding the token
        b = TokenClient("127.0.0.1", tokend["port"], "ns/pod-b")
        quota = b.acquire()  # must not deadlock
        assert quota > 0
        b.release(1.0)
        b.close()

    def test_config_reload(self, tokend):
        # new pod appears in config; tokend picks it up via inotify
        write_atomic(
            str(tokend["config_dir"] / tokend["uuid"]),
            "1\nns/pod-c 0.5 0.2 12345\n",
        )
        time.sleep(1.0)
        client = TokenClient("127.0.0.1", tokend["port"], "ns/pod-c")
        client.acquire()
        client.release(1.0)
        stat = client.stat()
        assert '"ns/pod-c"' in stat and '"mem_cap":12345' in stat
        client.close()

    def test_share_enforcement(self, tokend):
        """A greedy pod and a modest pod contend; grants must respect the
        guarantee ordering (pod-a request 0.5 vs pod-b 0.3)."""
        counts = {"ns/pod-a": 0, "ns/pod-b": 0}
        stop = time.monotonic() + 2.0

        def worker(pod):
            client = TokenClient("127.0.0.1", tokend["port"], pod)
            while time.monotonic() < stop:
                client.acquire()
                time.sleep(0.01)  # simulate 10ms of chip work
                client.release(10.0)
                counts[pod] += 1
            client.close()

        threads = [threading.Thread(target=worker, args=(p,)) for p in counts]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        total = sum(counts.values())
        assert total > 50  # token churn is cheap
        # both made progress; a's guaranteed share is larger
        assert counts["ns/pod-a"] > 0 and counts["ns/pod-b"] > 0
        share_a = counts["ns/pod-a"] / total
        assert share_a >= 0.45  # got at least ~its request share


class TestPmgr:
    def test_identity_stamping(self, tokend):
        pmgr_port = free_port()
        env = dict(
            os.environ,
            SCHEDULER_IP="127.0.0.1",
            SCHEDULER_PORT=str(tokend["port"]),
            POD_MANAGER_IP="127.0.0.1",
            POD_MANAGER_PORT=str(pmgr_port),
            POD_NAME="ns/pod-a",
        )
        proc = subprocess.Popen([PMGR], env=env, stderr=subprocess.DEVNULL)
        try:
            wait_listening(pmgr_port)
            # client lies about its pod name; pmgr stamps the real one
            client = TokenClient("127.0.0.1", pmgr_port, "ns/pod-b")
            client.acquire()
            client.release(2.0)
            stat = client.stat()
            assert '"ns/pod-a":{' in stat
            # pod-a accounted the grant, pod-b didn't
            import json

            pods = json.loads(stat)["pods"]
            assert pods["ns/pod-a"]["grants"] == 1
            assert pods.get("ns/pod-b", {}).get("grants", 0) == 0
            client.close()
        finally:
            proc.kill()
            proc.wait()


class TestNativeClient:
    def test_ctypes_client(self, tokend):
        client = NativeTokenClient("127.0.0.1", tokend["port"], "ns/pod-a")
        quota = client.acquire(1.0)
        assert quota > 0
        client.release(2.0)
        ok, _, _ = client.request_memory(1000)
        assert ok
        client.close()


class TestSupervisor:
    def test_end_to_end(self, tmp_path):
        """configd-style files -> supervisor -> tokend + pmgr -> client."""
        config_dir = tmp_path / "config"
        port_dir = tmp_path / "ports"
        config_dir.mkdir(); port_dir.mkdir()
        uuid = "chip-0"
        tokend_port = free_port()
        pmgr_port = free_port()
        write_atomic(str(config_dir / uuid), "1\nns/p1 1.0 0.5 1000\n")
        write_atomic(str(port_dir / uuid), f"1\nns/p1 {pmgr_port}\n")
        with ChipSupervisor(
            uuid,
            config_dir=str(config_dir),
            port_dir=str(port_dir),
            tokend_port=tokend_port,
            poll_interval=0.1,
        ) as supervisor:
            wait_listening(tokend_port)
            wait_listening(pmgr_port)
            client = TokenClient("127.0.0.1", pmgr_port, "ignored")
            assert client.acquire() > 0
            client.release(1.0)
            client.close()
            # pod removed -> pmgr reaped
            write_atomic(str(port_dir / uuid), "0\n")
            deadline = time.time() + 5
            while supervisor.pod_managers and time.time() < deadline:
                time.sleep(0.1)
            assert not supervisor.pod_managers


class TestGuard:
    def test_guard_gates_and_measures(self, tokend):
        client = TokenClient("127.0.0.1", tokend["port"], "ns/pod-a")
        guard = ExecutionGuard(client=client, from_env=False)
        calls = []

        @guard
        def step(x):
            calls.append(x)
            time.sleep(0.005)
            return x * 2

        assert step(21) == 42
        assert guard.tokens_acquired == 1
        assert guard.total_gated_ms >= 5.0
        client.close()

    def test_guard_passthrough_without_broker(self):
        guard = ExecutionGuard(client=None, from_env=False)
        assert not guard.gated

        @guard
        def step(x):
            return x + 1

        assert step(1) == 2

    def test_apply_hbm_cap(self):
        env = {"TPUSHARE_MEM_FRACTION": "0.5000"}
        assert apply_hbm_cap(env) == 0.5
        assert env["XLA_PYTHON_CLIENT_MEM_FRACTION"] == "0.5000"
        assert env["XLA_PYTHON_CLIENT_PREALLOCATE"] == "false"
        assert apply_hbm_cap({}) is None
        assert apply_hbm_cap({"TPUSHARE_MEM_FRACTION": "2.0"}) is None


class TestServingLedgerWiring:
    """The serving plane's transfer-byte hook -> tokend MEM verb: every
    KV byte the disaggregated engine stages host-side (tier demotes,
    promotions, prefill->decode chain migrations) can be charged through
    ``TokenClient.request_memory`` — the same fractional-HBM ledger the
    LD_PRELOAD shim debits for ``PJRT_Buffer_CopyToDevice``, so a pod's
    cache-tier traffic is accounted like any other device copy."""

    def test_disagg_ledger_hook_charges_and_credits_broker(self, tokend):
        import json

        import jax
        import jax.numpy as jnp
        import numpy as np

        from kubeshare_tpu.models.transformer import (TransformerConfig,
                                                      transformer_init)
        from kubeshare_tpu.serving import DisaggRouter, EngineConfig, Request

        client = TokenClient("127.0.0.1", tokend["port"], "ns/pod-a")
        moved = []

        def hook(nbytes, kind):
            # charge the staging copy, credit it once landed — the
            # transient CopyToDevice shape; a persistent-cache policy
            # would keep the charge until the tier entry dies
            ok, used, cap = client.request_memory(nbytes)
            assert ok, (kind, nbytes, used, cap)
            ok, _, _ = client.request_memory(-nbytes)
            assert ok
            moved.append((kind, nbytes))

        config = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq_len=64, dtype=jnp.float32, attention="reference")
        params = transformer_init(jax.random.PRNGKey(0), config)
        router = DisaggRouter(
            params, config,
            EngineConfig(num_slots=2, block_size=4, num_blocks=17,
                         max_request_len=48, prefill_chunk=8, mixed=False),
            EngineConfig(num_slots=2, block_size=4, num_blocks=13,
                         max_request_len=48, prefill_chunk=8, mixed=False),
            shared_tier_bytes=1 << 20, ledger_hook=hook)
        router.warmup()
        rng = np.random.default_rng(3)
        for i in range(4):
            router.submit(Request(
                f"r{i}", rng.integers(0, 64, 12).astype(np.int32), 6))
        router.run()
        kinds = {k for k, _ in moved}
        assert "migrate" in kinds and "demote" in kinds
        assert sum(n for k, n in moved if k == "migrate") \
            == router.migrator.migrated_bytes
        # every charge was credited: the broker ledger is back to zero
        stat = json.loads(client.stat())["pods"]["ns/pod-a"]
        assert stat["mem_used"] == 0
        client.close()


class TestInterposer:
    """LD_PRELOAD path: a driver dlopens a fake PJRT plugin the way JAX
    loads libtpu; libtpushim must gate every Execute through the tokend."""

    def _paths(self):
        base = os.path.join(os.path.dirname(__file__), "..", "native", "build")
        shim = os.path.abspath(os.path.join(base, "libtpushim.so.1"))
        plugin = os.path.abspath(os.path.join(base, "fake_pjrt_plugin.so"))
        driver = os.path.abspath(os.path.join(base, "interposer_driver"))
        if not all(os.path.exists(p) for p in (shim, plugin, driver)):
            pytest.skip("interposer fixtures not built (make -C native test-fixtures)")
        return shim, plugin, driver

    def _run_driver(self, tokend, driver_args, extra_env=None, pod="ns/pod-a"):
        """Start a pmgr for `pod`, run the driver under LD_PRELOAD, return
        (CompletedProcess, stat_dict)."""
        import json

        shim, plugin, driver = self._paths()
        pmgr_port = free_port()
        pmgr_env = dict(
            os.environ,
            SCHEDULER_IP="127.0.0.1",
            SCHEDULER_PORT=str(tokend["port"]),
            POD_MANAGER_IP="127.0.0.1",
            POD_MANAGER_PORT=str(pmgr_port),
            POD_NAME=pod,
        )
        pmgr = subprocess.Popen([PMGR], env=pmgr_env, stderr=subprocess.DEVNULL)
        try:
            wait_listening(pmgr_port)
            env = dict(
                os.environ,
                LD_PRELOAD=shim,
                POD_MANAGER_IP="127.0.0.1",
                POD_MANAGER_PORT=str(pmgr_port),
                POD_NAME=pod,
            )
            env.update(extra_env or {})
            out = subprocess.run(
                [driver, plugin] + driver_args, env=env, capture_output=True,
                text=True, timeout=60,
            )
            client = TokenClient("127.0.0.1", tokend["port"], "x")
            stat = json.loads(client.stat())
            client.close()
            return out, stat
        finally:
            pmgr.kill()
            pmgr.wait()

    def test_preload_gates_execute(self, tokend):
        out, stat = self._run_driver(tokend, ["7"])
        assert out.returncode == 0, out.stderr
        assert "executed 7 real_calls 7 buffers 1" in out.stdout
        # every execute acquired a token: grants visible in tokend
        pods = stat["pods"]
        assert pods["ns/pod-a"]["grants"] == 7
        # HBM accounting: 4096-byte upload charged then credited on
        # destroy -> net zero but the path executed
        assert pods["ns/pod-a"]["mem_used"] == 0

    def test_hard_hbm_denial(self, tokend):
        """An over-cap upload must come back as a fabricated
        RESOURCE_EXHAUSTED (code 8) PJRT error and never reach the plugin
        (VERDICT r1 #2: Gemini rejects over-cap allocs; matching semantics)."""
        out, stat = self._run_driver(
            tokend, ["0", "--upload-bytes", "2000000"]  # cap is 1000000
        )
        assert out.returncode == 0, out.stderr
        assert "upload_denied code=8" in out.stdout
        assert "HBM cap exceeded" in out.stdout
        # the real plugin never saw the allocation
        assert "buffers 0" in out.stdout
        assert stat["pods"]["ns/pod-a"]["mem_used"] == 0

    def test_soft_mode_logs_and_allows(self, tokend):
        out, stat = self._run_driver(
            tokend, ["0", "--upload-bytes", "2000000"],
            extra_env={"TPUSHARE_MEM_ENFORCE": "soft"},
        )
        assert out.returncode == 0, out.stderr
        assert "upload_ok" in out.stdout
        assert "buffers 1" in out.stdout
        # denied charge is not recorded (and thus never mis-credited)
        assert stat["pods"]["ns/pod-a"]["mem_used"] == 0

    def test_within_cap_charge_persists_until_destroy(self, tokend):
        out, stat = self._run_driver(
            tokend, ["0", "--upload-bytes", "500000", "--keep-buffer"]
        )
        assert out.returncode == 0, out.stderr
        assert "upload_ok" in out.stdout
        assert stat["pods"]["ns/pod-a"]["mem_used"] == 500000

    def test_async_transfer_over_cap_denied(self, tokend):
        """VERDICT r4 #2: the async host-to-device path
        (CreateBuffersForAsyncHostToDevice) must be metered like an
        upload — an over-cap create comes back RESOURCE_EXHAUSTED without
        reaching the plugin."""
        out, stat = self._run_driver(
            tokend, ["0", "--async-upload", "2000000"]  # cap is 1000000
        )
        assert out.returncode == 0, out.stderr
        assert "async_create_denied code=8" in out.stdout
        assert stat["pods"]["ns/pod-a"]["mem_used"] == 0

    def test_async_transfer_credited_on_destroy(self, tokend):
        """A completed async transfer cycle (create at cap -> retrieve ->
        manager destroy -> buffer destroy) must credit the broker in
        full: the subsequent plain upload AT the cap succeeds only if the
        ledger returned to zero."""
        out, stat = self._run_driver(
            tokend, ["0", "--async-upload", "1000000",
                     "--upload-bytes", "1000000"]
        )
        assert out.returncode == 0, out.stderr
        assert "async_create_ok" in out.stdout
        assert "async_retrieve_ok" in out.stdout
        assert "tm_destroyed" in out.stdout
        assert "async_buffer_destroyed" in out.stdout
        assert "upload_ok" in out.stdout
        assert stat["pods"]["ns/pod-a"]["mem_used"] == 0

    def test_async_transfer_unretrieved_credited_by_manager_destroy(
            self, tokend):
        """Buffers never retrieved die with the transfer manager; its
        destroy must credit their share."""
        out, stat = self._run_driver(
            tokend, ["0", "--async-upload", "1000000", "--async-no-retrieve",
                     "--upload-bytes", "1000000"]
        )
        assert out.returncode == 0, out.stderr
        assert "async_create_ok" in out.stdout
        assert "async_retrieve_ok" not in out.stdout
        assert "tm_destroyed" in out.stdout
        assert "upload_ok" in out.stdout
        assert stat["pods"]["ns/pod-a"]["mem_used"] == 0

    def test_dma_map_metered(self, tokend):
        """PJRT_Client_DmaMap makes a host region device-visible; it is
        charged like an upload (cap-every-alloc posture) and credited on
        DmaUnmap."""
        out, stat = self._run_driver(
            tokend, ["0", "--dma-map", "2000000"]  # cap is 1000000
        )
        assert out.returncode == 0, out.stderr
        assert "dma_map_denied code=8" in out.stdout
        assert stat["pods"]["ns/pod-a"]["mem_used"] == 0
        out, stat = self._run_driver(
            tokend, ["0", "--dma-map", "1000000",
                     "--upload-bytes", "1000000"]
        )
        assert out.returncode == 0, out.stderr
        assert "dma_map_ok" in out.stdout
        assert "dma_unmapped" in out.stdout
        assert "upload_ok" in out.stdout
        assert stat["pods"]["ns/pod-a"]["mem_used"] == 0

    def test_copy_to_device_over_cap_denied(self, tokend):
        """VERDICT r5 #3: PJRT_Buffer_CopyToDevice allocates a same-size
        target buffer — an over-cap copy must come back RESOURCE_EXHAUSTED
        without reaching the plugin.  FAKE_OUTPUT_BYTES sizes the fake's
        OnDeviceSizeInBytes, i.e. the charge the shim computes for the
        copy (cap is 1000000; 600000 source + 600000 copy > cap)."""
        out, stat = self._run_driver(
            tokend, ["0", "--upload-bytes", "600000", "--keep-buffer",
                     "--copy"],
            extra_env={"FAKE_OUTPUT_BYTES": "600000"},
        )
        assert out.returncode == 0, out.stderr
        assert "upload_ok" in out.stdout
        assert "copy_denied code=8" in out.stdout
        # only the upload's charge stands; the denied copy never ran
        assert stat["pods"]["ns/pod-a"]["mem_used"] == 600000

    def test_copy_to_device_charged_and_credited(self, tokend):
        """A within-cap copy is charged at the source's size and its
        destroy credits exactly that: the ledger returns to the kept
        upload's charge alone."""
        out, stat = self._run_driver(
            tokend, ["0", "--upload-bytes", "400000", "--keep-buffer",
                     "--copy"],
            extra_env={"FAKE_OUTPUT_BYTES": "400000"},
        )
        assert out.returncode == 0, out.stderr
        assert "copy_ok" in out.stdout
        assert "copy_destroyed" in out.stdout
        assert stat["pods"]["ns/pod-a"]["mem_used"] == 400000

    def test_copy_charge_persists_until_destroy(self, tokend):
        out, stat = self._run_driver(
            tokend, ["0", "--upload-bytes", "400000", "--keep-buffer",
                     "--copy", "--keep-copy"],
            extra_env={"FAKE_OUTPUT_BYTES": "400000"},
        )
        assert out.returncode == 0, out.stderr
        assert "copy_ok" in out.stdout
        assert "copy_destroyed" not in out.stdout
        assert stat["pods"]["ns/pod-a"]["mem_used"] == 800000

    def test_view_of_device_buffer_is_zero_size(self, tokend):
        """VERDICT r5 #3: CreateViewOfDeviceBuffer wraps memory someone
        else allocated — the view is accounted explicitly as aliased /
        zero-size: creating it charges nothing and destroying it credits
        nothing (the kept upload's charge must survive both)."""
        out, stat = self._run_driver(
            tokend, ["0", "--upload-bytes", "500000", "--keep-buffer",
                     "--view"],
        )
        assert out.returncode == 0, out.stderr
        assert "view_ok" in out.stdout
        assert "view_destroyed" in out.stdout
        assert stat["pods"]["ns/pod-a"]["mem_used"] == 500000

    def test_completion_time_charging(self, tokend):
        """Async dispatch: the fake device acks Execute instantly but is
        busy 50ms per program.  Charged time must track the device span
        (~3x50ms), not the dispatch wall time (~0ms) (VERDICT r1 #3)."""
        out, stat = self._run_driver(
            tokend, ["3", "--sleep-ms", "600"],
            extra_env={"FAKE_DEVICE_MS": "50"},
        )
        assert out.returncode == 0, out.stderr
        pod = stat["pods"]["ns/pod-a"]
        assert pod["grants"] == 3
        # dispatch-time charging would total well under 10ms here
        assert pod["charged_total_ms"] >= 100, stat

    def test_caller_owned_completion_events(self, tokend):
        """When the runtime's caller requests device_complete_events
        itself, the shim must piggyback (second OnReady callback) without
        stealing or destroying the caller's events."""
        out, stat = self._run_driver(
            tokend, ["3", "--events", "--sleep-ms", "400"],
            extra_env={"FAKE_DEVICE_MS": "30"},
        )
        assert out.returncode == 0, out.stderr
        assert "events_ready 3" in out.stdout
        pod = stat["pods"]["ns/pod-a"]
        assert pod["grants"] == 3
        assert pod["charged_total_ms"] >= 60, stat

    def test_preload_ungated_without_env(self, tokend):
        shim, plugin, driver = self._paths()
        env = {k: v for k, v in os.environ.items() if k != "POD_MANAGER_PORT"}
        env["LD_PRELOAD"] = shim
        out = subprocess.run(
            [driver, plugin, "3"], env=env, capture_output=True, text=True,
            timeout=60,
        )
        assert out.returncode == 0, out.stderr
        assert "executed 3 real_calls 3 buffers 1" in out.stdout

    def test_executable_outputs_charged(self, tokend):
        """Execute's output buffers allocate HBM without any upload hook:
        the shim must charge them on first sighting (VERDICT r2 #1)."""
        out, stat = self._run_driver(
            tokend, ["1", "--outputs", "2"],
            extra_env={"FAKE_OUTPUT_BYTES": "300000"},
        )
        assert out.returncode == 0, out.stderr
        assert "outputs_collected 2" in out.stdout
        # both outputs held at exit -> 2 x 300000 still charged
        assert stat["pods"]["ns/pod-a"]["mem_used"] == 600000

    def test_outputs_over_cap_deny_until_destroy(self, tokend):
        """Outputs pushing past the cap flip the pod into an over-cap state:
        the next execute AND the next upload are denied (RESOURCE_EXHAUSTED)
        until output destroys clear the overflow (VERDICT r2 #1 'done'
        criterion: a compiled program's outputs push past the cap and the
        next upload/execute is denied)."""
        out, stat = self._run_driver(
            tokend, ["3", "--outputs", "1"],
            extra_env={"FAKE_OUTPUT_BYTES": "600000"},  # cap 1000000
        )
        assert out.returncode == 0, out.stderr
        # execute 0: output charged (600000 <= cap)
        # execute 1: runs, but its output is DENIED -> overflow
        # execute 2: denied outright - the pod is over cap
        assert "execute_denied i=2 code=8" in out.stdout
        assert "real_calls 2" in out.stdout
        # the upload after the executes is denied too
        assert "upload_denied code=8" in out.stdout
        assert "buffers 0" in out.stdout
        # broker ledger holds only the granted charge, never over cap
        assert stat["pods"]["ns/pod-a"]["mem_used"] == 600000

    def test_output_destroy_recovers_over_cap(self, tokend):
        """Destroying the over-cap outputs clears the overflow: the upload
        that follows goes through and the ledger returns to zero."""
        out, stat = self._run_driver(
            tokend,
            ["2", "--outputs", "1", "--destroy-outputs"],
            extra_env={"FAKE_OUTPUT_BYTES": "600000"},
        )
        assert out.returncode == 0, out.stderr
        assert "outputs_destroyed 2" in out.stdout
        assert "upload_ok" in out.stdout
        assert stat["pods"]["ns/pod-a"]["mem_used"] == 0

    def test_soft_mode_outputs_account_but_allow(self, tokend):
        """Soft mode: over-cap outputs are logged + tracked, nothing is
        denied — the operator-observability mode keeps working."""
        out, stat = self._run_driver(
            tokend, ["3", "--outputs", "1"],
            extra_env={"FAKE_OUTPUT_BYTES": "600000",
                       "TPUSHARE_MEM_ENFORCE": "soft"},
        )
        assert out.returncode == 0, out.stderr
        assert "execute_denied" not in out.stdout
        assert "real_calls 3" in out.stdout
        assert "upload_ok" in out.stdout

    def test_client_create_injects_allocator_cap(self, tokend):
        """PJRT_Client_Create must receive memory_fraction/preallocate
        create options so client-init preallocation obeys the pod's cap
        (SURVEY §7.4's TPU-specific hard part)."""
        out, _ = self._run_driver(
            tokend, ["0", "--create-client"],
            extra_env={"TPUSHARE_MEM_FRACTION": "0.5"},
        )
        assert out.returncode == 0, out.stderr
        assert "client_ok options=memory_fraction=0.5000;preallocate=false;" \
            in out.stdout

    def test_client_create_fail_open_on_rejected_options(self, tokend):
        """A plugin that rejects unknown create options must still get a
        working client: the shim retries without the injected options."""
        out, _ = self._run_driver(
            tokend, ["0", "--create-client"],
            extra_env={"TPUSHARE_MEM_FRACTION": "0.5",
                       "FAKE_REJECT_CREATE_OPTIONS": "1"},
        )
        assert out.returncode == 0, out.stderr
        # retry succeeded; the recorded options from the final (bare) call
        # are empty, and the plugin saw exactly two creates
        assert "client_ok options= creates=2" in out.stdout
        assert "retrying without them" in out.stderr

    def test_client_create_error_propagated(self, tokend):
        """A create failure that is NOT option rejection (RESOURCE_EXHAUSTED
        here) must reach the caller unchanged with no bare retry — a blind
        retry would destroy the original error and hand a partially
        initialized plugin a second create (ADVICE r3)."""
        out, _ = self._run_driver(
            tokend, ["0", "--create-client"],
            extra_env={"TPUSHARE_MEM_FRACTION": "0.5",
                       "FAKE_CREATE_FAIL_CODE": "8"},
        )
        assert out.returncode == 0, out.stderr
        assert "client_err code=8" in out.stdout
        assert "creates=1" in out.stdout  # no second (bare) create
        assert "retrying without them" not in out.stderr

    def test_client_destroy_settles_ledgers(self, tokend):
        """Client destroy releases every buffer the client owns without
        per-buffer destroys: the shim must clear the charged + overflow
        ledgers and credit the broker, or a pod that re-creates its client
        stays over-cap (denied) for the process lifetime (ADVICE r3)."""
        out, stat = self._run_driver(
            tokend,
            ["3", "--outputs", "1", "--destroy-client"],
            extra_env={"FAKE_OUTPUT_BYTES": "600000"},  # cap 1000000
        )
        assert out.returncode == 0, out.stderr
        # over-cap before the destroy: the first upload is denied
        assert "upload_denied code=8" in out.stdout
        # destroy clears the overflow and credits the broker: the retry
        # upload goes through and is itself settled on buffer destroy
        assert "client_destroyed destroys=1" in out.stdout
        assert "upload2_ok" in out.stdout
        assert stat["pods"]["ns/pod-a"]["mem_used"] == 0

    def test_preload_exports_allocator_env(self, tokend):
        """The shim's constructor translates TPUSHARE_MEM_FRACTION into the
        XLA allocator env before the runtime starts — a preload-only pod
        (no kubeshare_tpu import) still gets its client allocator capped."""
        shim, _, _ = self._paths()
        out = subprocess.run(
            ["/bin/sh", "-c", "echo frac=$XLA_PYTHON_CLIENT_MEM_FRACTION "
             "prealloc=$XLA_PYTHON_CLIENT_PREALLOCATE"],
            env=dict(os.environ, LD_PRELOAD=shim,
                     TPUSHARE_MEM_FRACTION="0.3500"),
            capture_output=True, text=True, timeout=30,
        )
        assert out.returncode == 0, out.stderr
        assert "frac=0.3500 prealloc=false" in out.stdout


class TestTsan:
    """Race detection for the token scheduler: hammer a TSAN build with
    concurrent clients; any data race aborts the process / prints a
    ThreadSanitizer report."""

    def test_tokend_tsan_concurrent(self, tmp_path):
        tsan_binary = find_binary("tpushare-tokend-tsan")
        if tsan_binary is None:
            pytest.skip("tsan build not present (make -C native tsan)")
        config_dir = tmp_path / "config"
        config_dir.mkdir()
        write_atomic(str(config_dir / "chip-0"),
                     "2\nns/a 1.0 0.5 100000\nns/b 1.0 0.3 100000\n")
        port = free_port()
        proc = subprocess.Popen(
            [tsan_binary, "-p", str(config_dir), "-f", "chip-0",
             "-P", str(port), "-q", "10", "-m", "2", "-w", "200"],
            stderr=subprocess.PIPE, text=True,
        )
        try:
            wait_listening(port)

            def hammer(pod):
                client = TokenClient("127.0.0.1", port, pod)
                stop = time.monotonic() + 2.0
                while time.monotonic() < stop:
                    client.acquire()
                    client.release(1.0)
                    client.request_memory(10)
                    client.request_memory(-10)
                client.close()

            threads = [threading.Thread(target=hammer, args=(p,))
                       for p in ("ns/a", "ns/b", "ns/a", "ns/b")]
            for t in threads:
                t.start()
            # concurrent config reloads while clients hammer
            for i in range(5):
                write_atomic(str(config_dir / "chip-0"),
                             f"2\nns/a 1.0 0.{4+i%3} 100000\nns/b 1.0 0.3 100000\n")
                time.sleep(0.3)
            for t in threads:
                t.join()
            assert proc.poll() is None, "tokend died under TSAN"
        finally:
            proc.kill()
            _, stderr = proc.communicate(timeout=10)
        assert "ThreadSanitizer" not in (stderr or ""), stderr


class TestIdleRelease:
    def test_idle_guard_returns_token(self, tokend_exclusive):
        """A guard holding a budgeted token but gone idle must release it so
        co-tenants are not starved (exclusive mode makes this observable)."""
        a = TokenClient("127.0.0.1", tokend_exclusive["port"], "ns/pod-a")
        guard = ExecutionGuard(client=a, from_env=False, idle_release_ms=100)
        guard.acquire()
        guard.charge(1.0)  # budget remains -> token still held
        # pod-b blocks while a holds; after idle release it proceeds
        b = TokenClient("127.0.0.1", tokend_exclusive["port"], "ns/pod-b")
        granted = []

        def try_b():
            b.acquire()
            granted.append(1)
            b.release(1.0)

        t = threading.Thread(target=try_b)
        t.start()
        time.sleep(0.05)
        assert not granted  # still held
        t.join(timeout=5)   # idle monitor releases within ~100ms
        assert granted
        a.close(); b.close()

    def test_no_release_while_step_in_flight(self, tokend_exclusive):
        """A long step (e.g. first-step compile) between acquire and charge
        must not be treated as idleness."""
        a = TokenClient("127.0.0.1", tokend_exclusive["port"], "ns/pod-a")
        guard = ExecutionGuard(client=a, from_env=False, idle_release_ms=80)
        guard.acquire()  # step begins; no charge yet
        time.sleep(0.4)  # "compiling"
        b = TokenClient("127.0.0.1", tokend_exclusive["port"], "ns/pod-b")
        granted = []
        t = threading.Thread(target=lambda: (b.acquire(), granted.append(1),
                                             b.release(1.0)))
        t.start()
        time.sleep(0.1)
        assert not granted  # still held through the in-flight step
        guard.charge(1.0)  # step ends; budget remains -> held but idle now
        t.join(timeout=5)  # idle monitor releases
        assert granted
        a.close(); b.close()


class TestSupervisorMetrics:
    def test_tokend_stat_as_prometheus(self, tmp_path):
        import urllib.request

        config_dir = tmp_path / "config"
        port_dir = tmp_path / "ports"
        config_dir.mkdir(); port_dir.mkdir()
        write_atomic(str(config_dir / "chip-0"), "1\nns/p 1.0 0.5 4096\n")
        write_atomic(str(port_dir / "chip-0"), "0\n")
        tokend_port = free_port()
        with ChipSupervisor("chip-0", config_dir=str(config_dir),
                            port_dir=str(port_dir), tokend_port=tokend_port,
                            poll_interval=0.2) as sup:
            wait_listening(tokend_port)
            client = TokenClient("127.0.0.1", tokend_port, "ns/p")
            client.acquire(); client.release(5.0)
            client.request_memory(1000)
            client.close()
            server = sup.serve_metrics(port=0)
            try:
                body = urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/metrics", timeout=5
                ).read().decode()
                assert 'tpushare_pod_grants_total{chip="chip-0",pod="ns/p"} 1' in body
                assert 'tpushare_pod_mem_used_bytes{chip="chip-0",pod="ns/p"} 1000' in body
                assert "tpushare_pod_share" in body
            finally:
                server.stop()

    def test_config_reload_preserves_usage(self, tokend):
        # accumulate usage, then rewrite the config (same pod, new limits):
        # the decayed usage must survive the reload (no accounting reset)
        import json

        client = TokenClient("127.0.0.1", tokend["port"], "ns/pod-a")
        client.acquire()
        client.release(200.0)  # 200ms of a 1000ms window -> share ~0.2
        write_atomic(
            str(tokend["config_dir"] / tokend["uuid"]),
            "2\nns/pod-a 0.9 0.4 1000000\nns/pod-b 1.0 0.3 500000\n",
        )
        time.sleep(1.0)  # inotify reload + decay
        stat = json.loads(client.stat())
        pod_a = stat["pods"]["ns/pod-a"]
        assert pod_a["limit"] == 0.9  # new config applied
        assert pod_a["share"] > 0.05  # usage not reset (decayed from 0.2)
        client.close()


# ---------------------------------------------------------------------------
# Gang-aware coordination across sibling tokends (tokend -G; VERDICT r1 #9)
# ---------------------------------------------------------------------------

def _raw_cmd(port, line):
    with socket.create_connection(("127.0.0.1", port), timeout=5) as sock:
        f = sock.makefile("rw", newline="\n")
        f.write(line + "\n")
        f.flush()
        return f.readline().strip()


def _start_gang_pair(tmp_path, exclusive=False):
    """Two sibling tokends: gang/pod-x shared on both chips, ns/heavy only
    on chip-0.  Each is launched with -G pointing at the other."""
    config_dir = tmp_path / "config"
    config_dir.mkdir(exist_ok=True)
    write_atomic(str(config_dir / "chip-0"),
                 "2\ngang/pod-x 1.0 0.4 0\nns/heavy 1.0 0.5 0\n")
    write_atomic(str(config_dir / "chip-1"),
                 "1\ngang/pod-x 1.0 0.4 0\n")
    ports = [free_port(), free_port()]
    procs = []
    for i in range(2):
        cmd = [TOKEND, "-p", str(config_dir), "-f", f"chip-{i}",
               "-P", str(ports[i]), "-q", "50", "-m", "5", "-w", "1000",
               "-G", str(ports[1 - i])]
        if exclusive:
            cmd.append("-x")
        procs.append(subprocess.Popen(cmd, stderr=subprocess.DEVNULL))
    for port in ports:
        wait_listening(port)
    return procs, ports


@pytest.fixture
def gang_pair(tmp_path):
    procs, ports = _start_gang_pair(tmp_path)
    yield ports
    for proc in procs:
        proc.kill()
        proc.wait()


@pytest.fixture
def gang_pair_exclusive(tmp_path):
    procs, ports = _start_gang_pair(tmp_path, exclusive=True)
    yield procs, ports
    for proc in procs:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


class TestGangTokend:
    def test_peer_ineligibility_blocks_grant(self, gang_pair):
        """A gang pod over its limit on chip-0 must WAIT on chip-1 too,
        even though chip-1 itself would grant — grants stay aligned."""
        port0, port1 = gang_pair
        c0 = TokenClient("127.0.0.1", port0, "gang/pod-x")
        c0.acquire()
        c0.release(2000.0)  # share 2.0 of a 1000ms window: over limit on chip-0
        reply = _raw_cmd(port1, "REQ gang/pod-x 0")
        assert reply.startswith("WAIT "), reply
        # decay restores eligibility on chip-0 -> chip-1 grants again
        deadline = time.time() + 5
        while time.time() < deadline:
            reply = _raw_cmd(port1, "REQ gang/pod-x 0")
            if reply.startswith("TOK "):
                break
            time.sleep(0.1)
        assert reply.startswith("TOK "), reply
        c0.close()

    def test_unshared_pod_not_constrained_by_peer(self, gang_pair):
        """ns/heavy exists only in chip-0's config; chip-1 answers the
        probe 'not mine' and chip-0 grants normally."""
        port0, _ = gang_pair
        reply = _raw_cmd(port0, "REQ ns/heavy 0")
        assert reply.startswith("TOK "), reply

    def test_elig_probe_does_not_create_state(self, gang_pair):
        import json

        port0, _ = gang_pair
        assert _raw_cmd(port0, "ELIG ns/never-seen").startswith("ELIG 1")
        stat = json.loads(_raw_cmd(port0, "STAT"))
        assert "ns/never-seen" not in stat["pods"]

    def test_holder_counts_as_eligible_exclusive(self, gang_pair_exclusive):
        """Sequential multi-chip acquisition in exclusive mode: the pod's
        own grant on chip-0 must not block its REQ on chip-1 (the probe
        reports a holder as eligible)."""
        _, (port0, port1) = gang_pair_exclusive
        c0 = TokenClient("127.0.0.1", port0, "gang/pod-x")
        c0.acquire()  # holds chip-0 exclusively
        reply = _raw_cmd(port1, "REQ gang/pod-x 0")
        assert reply.startswith("TOK "), reply
        c0.release(1.0)
        c0.close()

    def test_fail_open_when_peer_dies(self, gang_pair_exclusive):
        """A dead sibling must not stall the chip: queries fail open."""
        procs, (port0, port1) = gang_pair_exclusive
        procs[1].kill()
        procs[1].wait()
        reply = _raw_cmd(port0, "REQ gang/pod-x 0")
        assert reply.startswith("TOK "), reply

    def test_gang_grants_align_under_independent_clients(self, gang_pair):
        """VERDICT r1 #9 criterion: per-chip grants stay within one
        quantum.  Driven by *independent* per-chip clients (NOT the
        pairwise GangTokenClient, whose symmetry would make alignment
        tautological): chip-1's client free-runs while chip-0's is
        throttled over limit — without -G chip-1 would rack up dozens of
        unilateral grants; with the gate its charged time may not run more
        than one quantum ahead of chip-0's."""
        import json

        port0, port1 = gang_pair
        # drive pod-x over its limit on chip-0 (share 2.0 of window 1.0)
        c0 = TokenClient("127.0.0.1", port0, "gang/pod-x")
        c0.acquire()
        c0.release(2000.0)
        charged0 = json.loads(
            _raw_cmd(port0, "STAT"))["pods"]["gang/pod-x"]["charged_total_ms"]
        # an independent client hammers chip-1 for ~0.4 s (well inside the
        # ~0.7 s decay time chip-0 needs to become eligible again)
        c1 = TokenClient("127.0.0.1", port1, "gang/pod-x")
        granted_ms = 0.0
        deadline = time.monotonic() + 0.4
        while time.monotonic() < deadline:
            reply = _raw_cmd(port1, "REQ gang/pod-x 0")
            if reply.startswith("TOK "):
                granted_ms += 30.0
                c1.release(30.0)  # keep holder count balanced if granted
                pytest.fail(
                    f"chip-1 granted unilaterally while chip-0 over limit: {reply}"
                )
            time.sleep(0.02)
        charged1 = json.loads(
            _raw_cmd(port1, "STAT"))["pods"]["gang/pod-x"]["charged_total_ms"]
        # chip-1 never ran ahead: within one base quantum (50 ms) of chip-0's
        # progress is trivially satisfied by zero unilateral grants
        assert charged1 <= granted_ms + 50.0
        assert charged0 >= 2000.0  # chip-0's charge actually landed
        c0.close()
        c1.close()

    def test_gang_client_env_construction(self, gang_pair, monkeypatch):
        """connect_from_env builds a gang client from comma-separated
        POD_MANAGER_PORT, members sorted by (host, port)."""
        from kubeshare_tpu.isolation.client import (GangTokenClient,
                                                    connect_from_env)

        port0, port1 = gang_pair
        monkeypatch.setenv("POD_MANAGER_PORT", f"{max(port0, port1)},{min(port0, port1)}")
        monkeypatch.setenv("POD_NAME", "gang/pod-x")
        monkeypatch.setenv("POD_MANAGER_IP", "127.0.0.1")
        client = connect_from_env()
        assert isinstance(client, GangTokenClient)
        assert [c.port for c in client.clients] == sorted([port0, port1])
        quota = client.acquire()
        assert quota > 0
        client.release(1.0)
        client.close()

    def test_native_client_gang_ports(self, gang_pair):
        """The C client (the LD_PRELOAD shim's transport) accepts the
        comma-separated gang port form and gates on EVERY broker — an
        atoi() of the list would silently gate only the first chip,
        bypassing isolation on the rest."""
        import json

        port0, port1 = gang_pair
        client = NativeTokenClient(
            "127.0.0.1", f"{port1},{port0}", "gang/pod-x"
        )
        quota = client.acquire(1.0)
        assert quota > 0
        client.release(10.0)
        ok, _, _ = client.request_memory(1 << 20)
        assert ok
        client.request_memory(-(1 << 20))
        client.close()
        for port in (port0, port1):  # both brokers saw the grant + charge
            pod = json.loads(_raw_cmd(port, "STAT"))["pods"]["gang/pod-x"]
            assert pod["grants"] == 1
            assert pod["charged_total_ms"] >= 10.0

    def test_cancel_pops_newest_grant(self, tokend):
        """CAN (gang unwind) must cancel the just-granted token, not
        FIFO-retire the oldest: the oldest may be legitimately in flight,
        and its later RET must carry its own measured charge."""
        import json

        c = TokenClient("127.0.0.1", tokend["port"], "ns/pod-a")
        q1 = c.acquire()   # token 1: in flight
        c.acquire()        # token 2: to be rolled back
        c.cancel()         # pops token 2 with zero charge
        stat = json.loads(c.stat())["pods"]["ns/pod-a"]
        assert stat["grants"] == 2
        assert stat["charged_total_ms"] == 0.0  # nothing retired yet
        c.release(q1 * 0.5)  # token 1 retires with its real charge
        stat = json.loads(c.stat())["pods"]["ns/pod-a"]
        assert abs(stat["charged_total_ms"] - q1 * 0.5) < 1e-6
        # holder count dropped to zero: no Abandon charge on disconnect
        c.close()
        time.sleep(0.2)
        reply = _raw_cmd(tokend["port"], "STAT")
        assert json.loads(reply)["holders"] == 0

    def test_elig_reply_carries_known_field(self, gang_pair):
        """ELIG's third field distinguishes 'eligible because unshared'
        (known=0, cacheable by the peer gate) from 'eligible and shared'
        (known=1)."""
        port0, _ = gang_pair
        assert _raw_cmd(port0, "ELIG ns/never-seen").split() == \
            ["ELIG", "1", "0.000000", "0"]
        reply = _raw_cmd(port0, "ELIG gang/pod-x").split()
        assert reply[0] == "ELIG" and reply[3] == "1"


def _start_gang_quad(tmp_path):
    """Four sibling tokends (a 2x2-slice-shaped gang): gang/pod-x shared on
    all four chips, each tokend launched with -G naming the other three."""
    config_dir = tmp_path / "config"
    config_dir.mkdir(exist_ok=True)
    for i in range(4):
        # 64 MiB per-chip HBM cap for the pod (config column 4, bytes)
        write_atomic(str(config_dir / f"chip-{i}"),
                     f"1\ngang/pod-x 1.0 0.4 {64 << 20}\n")
    ports = [free_port() for _ in range(4)]
    procs = []
    for i in range(4):
        peers = ",".join(str(ports[j]) for j in range(4) if j != i)
        procs.append(subprocess.Popen(
            [TOKEND, "-p", str(config_dir), "-f", f"chip-{i}",
             "-P", str(ports[i]), "-q", "50", "-m", "5", "-w", "1000",
             "-G", peers],
            stderr=subprocess.DEVNULL))
    for port in ports:
        wait_listening(port)
    return procs, ports


@pytest.fixture
def gang_quad(tmp_path):
    procs, ports = _start_gang_quad(tmp_path)
    yield ports
    for proc in procs:
        proc.kill()
        proc.wait()


class TestGangQuad:
    """-G past the pairwise fixture (VERDICT r2 #9): four live sibling
    tokends must keep grants aligned, and the gang client's unwind
    semantics must hold at width 4."""

    def test_one_overloaded_chip_blocks_all_three_peers(self, gang_quad):
        ports = gang_quad
        c0 = TokenClient("127.0.0.1", ports[0], "gang/pod-x")
        c0.acquire()
        c0.release(2000.0)  # share 2.0 of a 1.0 window: over limit on chip-0
        for port in ports[1:]:
            reply = _raw_cmd(port, "REQ gang/pod-x 0")
            assert reply.startswith("WAIT "), (port, reply)
        # decay restores chip-0 -> every peer grants again
        deadline = time.time() + 5
        granted = set()
        while time.time() < deadline and len(granted) < 3:
            for port in ports[1:]:
                if port not in granted and _raw_cmd(
                        port, "REQ gang/pod-x 0").startswith("TOK "):
                    granted.add(port)
            time.sleep(0.1)
        assert len(granted) == 3
        c0.close()

    def test_quad_soak_no_unilateral_runahead(self, gang_quad):
        """Contention soak: chip-0 is pushed over limit while independent
        clients hammer chips 1-3 for the whole decay window — none may
        grant unilaterally, so no chip's charged time runs ahead."""
        import json

        ports = gang_quad
        c0 = TokenClient("127.0.0.1", ports[0], "gang/pod-x")
        c0.acquire()
        c0.release(2000.0)

        errors = []

        def hammer(port):
            deadline = time.monotonic() + 0.4
            while time.monotonic() < deadline:
                reply = _raw_cmd(port, "REQ gang/pod-x 0")
                if reply.startswith("TOK "):
                    errors.append((port, reply))
                    return
                time.sleep(0.01)

        threads = [threading.Thread(target=hammer, args=(p,))
                   for p in ports[1:]]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, f"unilateral grants during overload: {errors}"
        for port in ports[1:]:
            charged = json.loads(_raw_cmd(port, "STAT"))[
                "pods"]["gang/pod-x"]["charged_total_ms"]
            assert charged == 0.0, (port, charged)
        c0.close()

    def test_gang_acquire_and_charge_spans_all_four(self, gang_quad):
        import json

        from kubeshare_tpu.isolation.client import GangTokenClient

        ports = gang_quad
        gang = GangTokenClient([
            TokenClient("127.0.0.1", p, "gang/pod-x") for p in ports
        ])
        quota = gang.acquire()
        assert quota > 0
        gang.release(25.0)
        for port in ports:
            pod = json.loads(_raw_cmd(port, "STAT"))["pods"]["gang/pod-x"]
            assert pod["grants"] == 1, (port, pod)
            assert pod["charged_total_ms"] >= 25.0
        gang.close()

    def test_mem_deny_on_last_chip_rolls_back_first_three(self, gang_quad):
        """HBM unwind at width 4: chip-3's ledger is pre-filled so the
        gang charge denies there — the three already-charged chips must be
        credited back, or the pod permanently loses headroom it never
        used."""
        ports = gang_quad
        mib = 1 << 20
        # fill chip-3 to 60 of the pod's 64 MiB per-chip cap
        reply = _raw_cmd(ports[3], f"MEM gang/pod-x {60 * mib}")
        assert reply.startswith("OK "), reply

        from kubeshare_tpu.isolation.client import GangTokenClient

        gang = GangTokenClient([
            TokenClient("127.0.0.1", p, "gang/pod-x") for p in ports
        ])
        ok, _, _ = gang.request_memory(8 * mib)  # fits on 0-2, not on 3
        assert not ok
        for port in ports[:3]:
            reply = _raw_cmd(port, "MEM gang/pod-x 0")
            used = int(reply.split()[1])
            assert used == 0, (port, reply)  # rolled back
        # chip-3 still holds only its pre-fill
        assert int(_raw_cmd(ports[3], "MEM gang/pod-x 0").split()[1]) \
            == 60 * mib
        gang.close()


class TestSupervisorGangWiring:
    def test_gang_peer_ports_reach_tokend_cmdline(self, tmp_path):
        sup = ChipSupervisor(
            chip_uuid="chip-0",
            config_dir=str(tmp_path / "config"),
            port_dir=str(tmp_path / "ports"),
            tokend_port=free_port(),
            gang_peer_ports=(49902, 49903),
            log_dir=str(tmp_path / "log"),
        )
        sup.start()
        try:
            with open(f"/proc/{sup.tokend.pid}/cmdline") as f:
                argv = f.read().split("\0")
            assert "-G" in argv
            assert argv[argv.index("-G") + 1] == "49902,49903"
        finally:
            sup.stop()
