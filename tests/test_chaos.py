"""Fault-injection suite: the serving plane under seeded chaos.

Every scenario here runs a real serving stack with a
:class:`~kubeshare_tpu.serving.chaos.FaultPlan` wired through the
chaos seams (no monkeypatching) and pins the recovery contract's
strongest form: the streams a chaos run emits are BIT-EXACT with the
fault-free run — greedy and sampled, through replica kills, hung
dispatches, dropped migration tickets, rotted tier bytes, and
transient tokend refusals.  Determinism is asserted too: replaying
the same plan over the same trace yields the same faults, fault for
fault, and the same streams.
"""

import socket
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeshare_tpu.models.transformer import TransformerConfig, transformer_init

pytestmark = [pytest.mark.serving, pytest.mark.chaos]


def _small_config(**extra):
    return TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=64, dtype=jnp.float32, attention="reference", **extra)


def _fleet(params, config, *, replicas=2, num_blocks=21, **overrides):
    from kubeshare_tpu.serving import EngineConfig, ReplicaFleet

    ec_kwargs = dict(num_slots=3, block_size=4, num_blocks=num_blocks,
                     max_request_len=48, prefill_chunk=8)
    fleet_kwargs = dict(replicas=replicas)
    for k in ("routing", "tenants", "shared_tier_bytes", "clock",
              "fault_clock", "liveness_grace", "watchdog_budget_s",
              "watchdog_grace", "fabric", "fabric_ttl_ticks"):
        if k in overrides:
            fleet_kwargs[k] = overrides.pop(k)
    ec_kwargs.update(overrides)
    return ReplicaFleet(params, config, EngineConfig(**ec_kwargs),
                        **fleet_kwargs)


def _metric(families, name, **labels):
    total = 0.0
    for fam in families:
        for s in fam.samples:
            if s.name == name and all(
                    s.labels.get(k) == v for k, v in labels.items()):
                total += s.value
    return total


def _mixed_trace():
    """Greedy AND sampled lanes over a shared-prefix family — the
    rng construction order is part of the trace, so both arms must
    call this identically."""
    from kubeshare_tpu.serving import Request

    rng = np.random.default_rng(5)
    shared = rng.integers(0, 64, 12)
    out = []
    for i in range(8):
        if i % 2 == 0:
            prompt = np.concatenate([shared, rng.integers(0, 64, 4)])
        else:
            prompt = rng.integers(0, 64, 10)
        key = (jax.random.PRNGKey(70 + i) if i % 3 == 0 else None)
        out.append(Request(
            f"r{i}", prompt, 6,
            temperature=(0.8 if key is not None else 0.0), rng=key))
    return out


class _PinFirst:
    """Route everything to the first live candidate — keeps the doomed
    replica's ownership deterministic."""

    def route(self, fleet, request, candidates):
        return candidates[0], "least_loaded"


class TestFaultPlan:
    def test_builders_validate_and_chain(self):
        from kubeshare_tpu.serving.chaos import FaultPlan

        plan = (FaultPlan(seed=7).kill("r1", at_step=4)
                .slow_dispatch("r0", at=2, seconds=0.5)
                .corrupt_tier_put(3).drop_ticket(0).refuse_tokend(2))
        assert plan.kills == {"r1": 4}
        assert plan.slow == {"r0": {2: 0.5}}
        assert plan.tier_corruptions == {3}
        assert plan.ticket_drops == {0}
        assert plan.tokend_refusals == {2}
        for bad in (lambda p: p.kill("x", -1),
                    lambda p: p.slow_dispatch("x", -1, 1.0),
                    lambda p: p.slow_dispatch("x", 0, 0.0),
                    lambda p: p.corrupt_tier_put(-1),
                    lambda p: p.drop_ticket(-1),
                    lambda p: p.refuse_tokend(-1)):
            with pytest.raises(ValueError):
                bad(FaultPlan())

    def test_corruption_is_seeded_length_preserving_and_detected(self):
        """The bit flip derives from (seed, ordinal): same plan rots
        the same bit on replay, a different seed rots a different one,
        and the wire crc catches either."""
        from kubeshare_tpu.serving import WireCorruption, pack_block, \
            unpack_block
        from kubeshare_tpu.serving.chaos import FaultClock, FaultPlan

        k = np.ones((2, 2, 4, 8), np.float32)
        payload = pack_block([1, 2, 3, 4], k, k)

        def rot(seed):
            clock = FaultClock(FaultPlan(seed=seed).corrupt_tier_put(0))
            return clock.on_tier_put(payload)

        a, b, c = rot(3), rot(3), rot(4)
        assert a == b and a != c and len(a) == len(payload)
        unpack_block(payload)  # pristine round-trips
        with pytest.raises(WireCorruption):
            unpack_block(a)
        # untargeted ordinals pass through untouched
        clock = FaultClock(FaultPlan(seed=3).corrupt_tier_put(5))
        assert clock.on_tier_put(payload) == payload

    def test_virtual_clock_and_ordinal_counters(self):
        from kubeshare_tpu.serving.chaos import FaultClock, FaultPlan

        clock = FaultClock(FaultPlan(), step_dt=0.25)

        class Eng:
            replica_label = "r9"

        assert clock.now() == 0.0
        clock.on_engine_step(Eng())
        clock.on_engine_step(Eng())
        assert clock.now() == 0.5
        clock.advance(1.0)
        assert clock.now() == 1.5
        with pytest.raises(ValueError):
            clock.advance(-1.0)


class TestReplicaKillRecovery:
    def test_kill_mid_trace_bit_exact_greedy_and_sampled(self):
        """The tentpole contract: kill a replica mid-trace and every
        stream — greedy and sampled, including the dead replica's
        orphans — matches the fault-free fleet run token for token,
        with zero recompiles on the survivor."""
        from kubeshare_tpu.serving.chaos import FaultClock, FaultPlan

        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)

        def run_arm(fault_clock=None):
            fleet = _fleet(params, config, top_k=10, top_p=0.95,
                           shared_tier_bytes=1 << 20,
                           fault_clock=fault_clock)
            fleet.warmup()
            base = fleet.compile_counts()
            for r in _mixed_trace():
                fleet.submit(r)
            streams = {k: v.tokens for k, v in fleet.run().items()}
            return fleet, base, streams

        _, _, want = run_arm()
        clock = FaultClock(FaultPlan(seed=7).kill("r1", at_step=2))
        fleet, base, got = run_arm(clock)
        assert got == want
        assert fleet.replica_failures == {"liveness": 1}
        assert fleet._handle("r1").state == "failed"
        assert fleet._handle("r1").fail_cause == "liveness"
        assert fleet.orphans_readmitted > 0
        # zero recompiles on every SURVIVING replica
        after = fleet.compile_counts()
        for k, v in base.items():
            if not k.startswith("r1"):
                assert after[k] == v, k
        # the failure is visible through the metrics plane
        fams = fleet.collect_metrics()
        assert _metric(fams, "kubeshare_serving_fleet_replica_failures_total",
                       cause="liveness") == 1
        assert _metric(fams,
                       "kubeshare_serving_fleet_recovery_seconds_count") == 1
        assert _metric(fams, "kubeshare_serving_fleet_replicas",
                       state="failed") == 1

    def test_replay_same_plan_same_faults_same_streams(self):
        """Replayability is the chaos harness's own invariant: two runs
        of one plan over one trace agree fault-for-fault and
        token-for-token."""
        from kubeshare_tpu.serving.chaos import FaultClock, FaultPlan

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)

        def run_once():
            clock = FaultClock(FaultPlan(seed=7).kill("r1", at_step=3))
            fleet = _fleet(params, config, shared_tier_bytes=1 << 20,
                           fault_clock=clock)
            fleet.warmup()
            for r in _mixed_trace():
                fleet.submit(r)
            return clock.events, {k: v.tokens
                                  for k, v in fleet.run().items()}

        events_a, streams_a = run_once()
        events_b, streams_b = run_once()
        assert events_a == events_b
        assert streams_a == streams_b
        assert any(e[0] == "kill" for e in events_a)

    def test_orphan_lands_on_survivor_with_salvaged_prefix(self):
        """The dead replica's host-resident trie is salvage: the
        survivor adopts it through the SHARED tier, the orphan resumes
        there mid-stream, and the stream still matches the dense
        reference."""
        from kubeshare_tpu.models.decoding import greedy_decode
        from kubeshare_tpu.serving import Request
        from kubeshare_tpu.serving.chaos import FaultClock, FaultPlan

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        plan = FaultPlan(seed=11)
        clock = FaultClock(plan)
        fleet = _fleet(params, config, num_slots=2, num_blocks=13,
                       max_request_len=32, routing=_PinFirst(),
                       shared_tier_bytes=1 << 20, fault_clock=clock)
        fleet.warmup()
        rng = np.random.default_rng(13)
        shared = rng.integers(0, 64, 16)
        fleet.submit(Request(
            "warm", np.concatenate([shared, rng.integers(0, 64, 4)]), 4))
        fleet.run()
        owner = fleet.owner_of("warm")
        oeng = fleet._handle(owner).engine
        # eviction pressure demotes the warm prefix to the shared tier
        for i in range(3):
            fleet.submit(Request(f"p{i}", rng.integers(0, 64, 20), 4))
            fleet.run()
        assert oeng.tier_demoted_blocks > 0
        survivor = [h for h in fleet.replicas if h.name != owner][0]
        # an in-flight request on the doomed replica, killed mid-decode
        prompt = np.concatenate([shared, rng.integers(0, 64, 4)])
        fleet.submit(Request("orphan", prompt, 10))
        while True:
            slots = [s for s in oeng._slots
                     if s.rid == "orphan" and s.state == "decode"]
            if slots and len(slots[0].generated) >= 2:
                break
            assert fleet.step(), "fleet idle before the orphan decoded"
        plan.kill(owner, at_step=clock._steps.get(owner, 0))
        out = fleet.run()
        ref = np.asarray(greedy_decode(
            params, config, jnp.asarray(prompt, jnp.int32)[None], 10))[0]
        assert out["orphan"].tokens == list(ref)
        assert fleet.owner_of("orphan") == survivor.name
        assert fleet.salvaged_tokens > 0
        assert survivor.engine.prefix_match_len(shared) >= 16
        fams = fleet.collect_metrics()
        assert _metric(
            fams,
            "kubeshare_serving_fleet_salvaged_prefix_tokens_total") > 0
        assert _metric(
            fams, "kubeshare_serving_fleet_orphans_readmitted_total") >= 1


class TestSpecLoopChaos:
    """Verify-in-loop launches under chaos: a kill at the loop dispatch
    boundary must drain the in-flight K-unit token ring (and the
    admission ring's staged lanes) before orphan re-admission, and the
    fleet watchdog must budget a K-unit launch as K dispatches' work."""

    def _spec_trace(self):
        """Repetitive prompts so the n-gram drafter proposes on every
        lane — the decode phase goes all-drafted and the engine plans
        verify-in-loop launches; greedy and sampled lanes mixed."""
        from kubeshare_tpu.serving import Request

        rng = np.random.default_rng(29)
        out = []
        for i in range(6):
            pat = rng.integers(0, 64, 4)
            prompt = np.concatenate([np.tile(pat, 3),
                                     rng.integers(0, 64, 2)])
            key = (jax.random.PRNGKey(80 + i) if i % 3 == 2 else None)
            out.append(Request(
                f"r{i}", prompt, 8,
                temperature=(0.8 if key is not None else 0.0), rng=key))
        return out

    def test_kill_at_loop_boundary_drains_ring_bit_exact(self):
        """Kill the replica exactly at a loop dispatch boundary — a
        K-unit verify-in-loop launch completed on the wire but its
        token ring never reached host state.  Recovery must drain it
        first (emissions, retirements, ring activations), then re-admit
        the orphans; every stream matches the fault-free run token for
        token, greedy and sampled."""
        from kubeshare_tpu.serving.chaos import FaultClock, FaultPlan

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)

        def build(fault_clock=None):
            fleet = _fleet(params, config, routing=_PinFirst(),
                           num_blocks=41, speculative=True,
                           steps_per_launch=4, admission_ring=2,
                           top_k=10, top_p=0.95, fault_clock=fault_clock)
            fleet.warmup()
            for r in self._spec_trace():
                fleet.submit(r)
            return fleet

        ref = build()
        want = {k: v.tokens for k, v in ref.run().items()}
        assert ref._handle("r0").engine.spec_loop_launches > 0, \
            "trace never engaged the spec loop"

        plan = FaultPlan(seed=31)
        clock = FaultClock(plan)
        fleet = build(clock)
        eng = fleet._handle("r0").engine
        while not (eng._inflight is not None
                   and eng._inflight[0] == "spec_loop"):
            assert fleet.step(), \
                "trace drained before a spec-loop launch was in flight"
        plan.kill("r0", at_step=clock._steps.get("r0", 0))
        got = {k: v.tokens for k, v in fleet.run().items()}
        assert got == want
        assert fleet.replica_failures == {"liveness": 1}
        # the in-flight launch was drained into host state before the
        # orphan walk: nothing left in flight, no staged lane stranded
        assert eng._inflight is None
        assert eng._ring_staged == []
        assert fleet.orphans_readmitted > 0

    def test_watchdog_budget_covers_k_unit_launches(self):
        """A healthy K-unit verify-in-loop launch legitimately takes K
        dispatches' worth of time in one step; the watchdog must budget
        it by the launch envelope instead of flagging it hung.  The
        injected delay is OVER the per-dispatch budget (a flat budget
        would kill the replica) but inside K times it."""
        from kubeshare_tpu.serving.chaos import FaultClock, FaultPlan

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)

        def build(fault_clock, **kw):
            return _fleet(params, config, routing=_PinFirst(),
                          num_blocks=41, speculative=True,
                          steps_per_launch=4, top_k=10, top_p=0.95,
                          fault_clock=fault_clock, **kw)

        # record pass: which of r0's dispatch ordinals are spec-loop
        # launches (the launch is the step's last dispatch)
        clock = FaultClock(FaultPlan(seed=37))
        fleet = build(clock)
        fleet.warmup()
        results = {}
        for r in self._spec_trace():
            results[r.rid] = fleet.submit(r)
        eng = fleet._handle("r0").engine
        loop_ordinals = []
        while fleet.step():
            if eng._inflight is not None \
                    and eng._inflight[0] == "spec_loop":
                loop_ordinals.append(clock._dispatches["r0"] - 1)
        want = {rid: res.tokens for rid, res in results.items()}
        assert loop_ordinals, "trace never engaged the spec loop"

        budget, delay = 0.05, 0.12
        assert delay > budget          # flat budget would trip...
        assert delay < 4 * budget      # ...the launch envelope must not
        plan = FaultPlan(seed=37)
        for n in loop_ordinals:
            plan.slow_dispatch("r0", n, delay)
        clock2 = FaultClock(plan)
        fleet2 = build(clock2, watchdog_budget_s=budget, watchdog_grace=1)
        fleet2.warmup()
        for r in self._spec_trace():
            fleet2.submit(r)
        got = {k: v.tokens for k, v in fleet2.run().items()}
        assert got == want
        assert fleet2.replica_failures == {}
        assert fleet2._handle("r0").state == "active"
        landed = sum(1 for e in clock2.events if e[0] == "slow_dispatch")
        assert landed == len(loop_ordinals)


class TestPlacementReclaim:
    TOPOLOGY = """
cellTypes:
  V4-NODE:
    childCellType: "TPU-v4"
    childCellNumber: 4
    childCellPriority: 60
    isNodeLevel: true
  2-V4-NODE:
    childCellType: V4-NODE
    childCellNumber: 2
cells:
- cellType: 2-V4-NODE
  cellChildren:
  - cellId: host-a
  - cellId: host-b
"""

    def test_crash_releases_cell_through_pod_deleted_path(self):
        """A killed replica's fractional cell is reclaimed exactly as a
        retirement's would be — through the placement plane's
        pod-deleted path — and the release-cause ledger says it was a
        crash, not planned churn."""
        from kubeshare_tpu import constants
        from kubeshare_tpu.cell import load_config
        from kubeshare_tpu.cell.allocator import ChipInfo
        from kubeshare_tpu.cluster.api import FakeClock, Node
        from kubeshare_tpu.cluster.fake import FakeCluster
        from kubeshare_tpu.scheduler import (FleetPlacementPlane,
                                             KubeShareScheduler,
                                             SchedulerArgs, SchedulerEngine)
        from kubeshare_tpu.serving import EngineConfig, ReplicaFleet, \
            Request
        from kubeshare_tpu.serving.chaos import FaultClock, FaultPlan

        hbm = 32 << 30
        inventory = {
            node: [ChipInfo(f"{node}-tpu-{i}", hbm, "TPU-v4", i,
                            (i, rank, 0)) for i in range(4)]
            for rank, node in enumerate(("host-a", "host-b"))
        }
        cluster = FakeCluster()
        for n in ("host-a", "host-b"):
            cluster.add_node(Node(
                name=n, labels={constants.NODE_LABEL_FILTER: "true"}))
        sched_clock = FakeClock(1000.0)
        plugin = KubeShareScheduler(
            topology=load_config(text=self.TOPOLOGY), cluster=cluster,
            inventory=lambda node: inventory.get(node, []),
            args=SchedulerArgs(), clock=sched_clock)
        engine = SchedulerEngine(plugin, cluster, sched_clock)
        plane = FleetPlacementPlane(engine, cluster, gpu_request="0.5",
                                    gpu_limit="0.5", gpu_memory=1 << 30,
                                    priority=10)

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        clock = FaultClock(FaultPlan(seed=5).kill("r1", at_step=1))
        fleet = ReplicaFleet(
            params, config,
            EngineConfig(num_slots=3, block_size=4, num_blocks=21,
                         max_request_len=48, prefill_chunk=8),
            replicas=2, placement=plane, fault_clock=clock)
        assert len(cluster.list_pods(namespace="serving")) == 2
        fleet.warmup()
        rng = np.random.default_rng(3)
        for i in range(4):
            fleet.submit(Request(f"q{i}", rng.integers(0, 64, 10), 4))
        out = fleet.run()
        assert fleet.replica_failures == {"liveness": 1}
        assert all(len(r.tokens) == 4 for r in out.values())
        # the dead replica's pod went through the pod-deleted reclaim
        assert len(cluster.list_pods(namespace="serving")) == 1
        assert plane.release_causes == {"liveness": 1}


class TestWatchdog:
    def _decode_dispatch_ordinal(self, fleet, clock, label, rid):
        """Park the target request in decode, then report the label's
        NEXT dispatch ordinal so planned delays land deterministically."""
        eng = fleet._handle(label).engine
        while True:
            slots = [s for s in eng._slots
                     if s.rid == rid and s.state == "decode"]
            if slots and len(slots[0].generated) >= 1:
                return clock._dispatches.get(label, 0)
            assert fleet.step(), "fleet idle before target decoded"

    def test_slow_dispatch_below_budget_is_not_a_failure(self):
        """A merely-slow replica must NOT be declared dead: repeated
        dispatches inside the budget never trip the watchdog."""
        from kubeshare_tpu.serving import Request
        from kubeshare_tpu.serving.chaos import FaultClock, FaultPlan

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        plan = FaultPlan(seed=3)
        clock = FaultClock(plan)
        fleet = _fleet(params, config, routing=_PinFirst(),
                       fault_clock=clock, watchdog_budget_s=0.05,
                       watchdog_grace=2)
        fleet.warmup()
        rng = np.random.default_rng(17)
        fleet.submit(Request("slowpoke", rng.integers(0, 64, 10), 12))
        n = self._decode_dispatch_ordinal(fleet, clock, "r0", "slowpoke")
        for k in range(4):  # slow but under budget, four steps running
            plan.slow_dispatch("r0", n + k, 0.02)
        out = fleet.run()
        assert fleet.replica_failures == {}
        assert fleet._handle("r0").state == "active"
        assert len(out["slowpoke"].tokens) == 12
        # at least one planned delay actually landed (step fusion may
        # finish the stream in fewer dispatches than tokens)
        assert sum(1 for e in clock.events if e[0] == "slow_dispatch") >= 1

    def test_hung_dispatch_trips_watchdog_and_stream_survives(self):
        """A hung replica makes 'progress' every step — only the clock
        catches it.  Consecutive over-budget steps hit the grace limit,
        the replica is failed with cause=watchdog, and its in-flight
        stream completes bit-exact on the survivor."""
        from kubeshare_tpu.models.decoding import greedy_decode
        from kubeshare_tpu.serving import Request
        from kubeshare_tpu.serving.chaos import FaultClock, FaultPlan

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        plan = FaultPlan(seed=3)
        clock = FaultClock(plan)
        fleet = _fleet(params, config, routing=_PinFirst(),
                       shared_tier_bytes=1 << 20, fault_clock=clock,
                       watchdog_budget_s=0.05, watchdog_grace=2)
        fleet.warmup()
        rng = np.random.default_rng(19)
        prompt = rng.integers(0, 64, 10)
        fleet.submit(Request("victim", prompt, 12))
        n = self._decode_dispatch_ordinal(fleet, clock, "r0", "victim")
        for k in range(4):  # hung: every dispatch blows the budget
            plan.slow_dispatch("r0", n + k, 10.0)
        out = fleet.run()
        assert fleet.replica_failures == {"watchdog": 1}
        assert fleet._handle("r0").fail_cause == "watchdog"
        ref = np.asarray(greedy_decode(
            params, config, jnp.asarray(prompt, jnp.int32)[None], 12))[0]
        assert out["victim"].tokens == list(ref)
        fams = fleet.collect_metrics()
        assert _metric(fams, "kubeshare_serving_fleet_replica_failures_total",
                       cause="watchdog") == 1
        # recovery latency includes the hang: at least the two
        # over-budget steps of virtual time
        assert _metric(fams,
                       "kubeshare_serving_fleet_recovery_seconds_sum") >= 20.0


class TestTierCorruption:
    def test_rotted_tier_bytes_are_a_loud_miss_not_wrong_tokens(self):
        """Corrupt EVERY byte-payload the shared tier stores: the
        survivor's promotion path must detect each rotted block
        (crc32), fall back to re-prefill, and still emit the exact
        dense streams — corruption costs latency, never correctness."""
        from kubeshare_tpu.models.decoding import greedy_decode
        from kubeshare_tpu.serving import Request
        from kubeshare_tpu.serving.chaos import FaultClock, FaultPlan

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        plan = FaultPlan(seed=23)
        for n in range(200):
            plan.corrupt_tier_put(n)
        clock = FaultClock(plan)
        fleet = _fleet(params, config, shared_tier_bytes=1 << 20,
                       fault_clock=clock)
        fleet.warmup()
        rng = np.random.default_rng(11)
        shared = rng.integers(0, 64, 16)
        fleet.submit(Request(
            "seed", np.concatenate([shared, rng.integers(0, 64, 4)]), 4))
        fleet.run()
        owner = fleet.owner_of("seed")
        survivor = [h for h in fleet.replicas if h.name != owner][0]
        fleet.drain(owner)
        fleet.run()
        # the retiree's trie reached the tier — rotted
        assert len(fleet.shared_tier._entries) > 0
        assert any(e[0] == "corrupt_put" for e in clock.events)
        prompt = np.concatenate([shared, rng.integers(0, 64, 4)])
        fleet.submit(Request("heir", prompt, 6))
        out = fleet.run()
        ref = np.asarray(greedy_decode(
            params, config, jnp.asarray(prompt, jnp.int32)[None], 6))[0]
        assert out["heir"].tokens == list(ref)
        assert survivor.engine.tier_corrupt_blocks > 0
        fams = fleet.collect_metrics()
        assert _metric(
            fams, "kubeshare_serving_tier_corruptions_total") > 0


class TestDisaggHandoffTTL:
    PREFILL = dict(num_slots=2, block_size=4, num_blocks=17,
                   max_request_len=48, prefill_chunk=8, mixed=False)
    DECODE = dict(num_slots=3, block_size=4, num_blocks=25,
                  max_request_len=48, prefill_chunk=8, mixed=False)

    def _router(self, params, config, **kwargs):
        from kubeshare_tpu.serving import DisaggRouter, EngineConfig

        return DisaggRouter(params, config, EngineConfig(**self.PREFILL),
                            EngineConfig(**self.DECODE), **kwargs)

    def _trace(self):
        rng = np.random.default_rng(61)
        return [dict(rid="long", prompt=rng.integers(0, 64, 29),
                     max_new_tokens=6),
                dict(rid="s0", prompt=rng.integers(0, 64, 5),
                     max_new_tokens=8),
                dict(rid="samp", prompt=rng.integers(0, 64, 11),
                     max_new_tokens=7, temperature=0.8,
                     rng=jax.random.PRNGKey(62))]

    def _mono_streams(self, params, config):
        from kubeshare_tpu.serving import EngineConfig, Request, \
            ServingEngine

        mono = ServingEngine(params, config, EngineConfig(
            num_slots=3, block_size=4, num_blocks=41, max_request_len=48,
            prefill_chunk=8, mixed=False))
        mono.warmup()
        for r in self._trace():
            mono.submit(Request(**r))
        return {k: v.tokens for k, v in mono.run().items()}

    def test_dropped_ticket_expires_releases_reserve_and_stays_exact(self):
        """The reserve-leak regression: a ticket whose deliveries keep
        dropping must EXPIRE — releasing its decode reserve (the
        admission gate counts pending tickets) and resuming the request
        through prefill-from-cache — instead of wedging the router.
        Streams stay bit-exact through drop, retry, expiry, and
        resume; the retry ledger tells the story."""
        from kubeshare_tpu.serving import Request
        from kubeshare_tpu.serving.chaos import FaultClock, FaultPlan

        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        want = self._mono_streams(params, config)

        plan = FaultPlan(seed=9)
        for n in (0, 1, 2):
            plan.drop_ticket(n)
        router = self._router(params, config, handoff_ttl_steps=3,
                              handoff_backoff_steps=1)
        router.fault_clock = FaultClock(plan)
        router.warmup()
        base = router.compile_counts()
        for r in self._trace():
            router.submit(Request(**r))
        got = {k: v.tokens for k, v in router.run().items()}
        assert got == want
        # reserve gauge back to baseline: no ticket left holding slots
        assert len(router._tickets) == 0
        assert router.handoff_retries["dropped"] == 3
        assert router.handoff_retries["expired"] >= 1
        assert router.compile_counts() == base
        fams = router.collect_metrics()
        assert _metric(fams, "kubeshare_serving_handoff_retries_total",
                       outcome="dropped") == 3
        assert _metric(fams, "kubeshare_serving_handoff_retries_total",
                       outcome="expired") >= 1

    def test_backoff_defers_redelivery_without_busy_spin(self):
        """A dropped delivery schedules the NEXT attempt exponentially
        later in router steps; the ticket eventually delivers and the
        ledger shows the retry."""
        from kubeshare_tpu.serving import Request
        from kubeshare_tpu.serving.chaos import FaultClock, FaultPlan

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        want = self._mono_streams(params, config)
        plan = FaultPlan(seed=9).drop_ticket(0)
        router = self._router(params, config, handoff_ttl_steps=50,
                              handoff_backoff_steps=2,
                              handoff_backoff_cap_steps=8)
        router.fault_clock = FaultClock(plan)
        router.warmup()
        for r in self._trace():
            router.submit(Request(**r))
        got = {k: v.tokens for k, v in router.run().items()}
        assert got == want
        assert router.handoff_retries["dropped"] == 1
        assert router.handoff_retries["expired"] == 0
        assert router.handoff_retries["delivered"] == len(self._trace())

    def test_ttl_constructor_validation(self):
        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        for kwargs in (dict(handoff_ttl_steps=0),
                       dict(handoff_backoff_steps=0),
                       dict(handoff_backoff_steps=4,
                            handoff_backoff_cap_steps=2)):
            with pytest.raises(ValueError):
                self._router(params, config, **kwargs)


class _OneShotServer:
    """A tokend stand-in: answers each connection's first line with a
    canned reply — enough to exercise the client's retry loop."""

    def __init__(self, replies):
        self._sock = socket.socket()
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(8)
        self.port = self._sock.getsockname()[1]
        self._thread = threading.Thread(
            target=self._serve, args=(list(replies),), daemon=True)
        self._thread.start()

    def _serve(self, replies):
        while replies:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            reply = replies.pop(0)
            f = conn.makefile("rw", newline="\n")
            if f.readline() and reply is not None:
                f.write(reply)
                f.flush()
            conn.close()

    def close(self):
        self._sock.close()


class TestTokendRetry:
    def test_transient_refusal_recovers_with_metered_retry(self):
        from kubeshare_tpu.isolation.client import TokenClient
        from kubeshare_tpu.serving.chaos import FaultClock, FaultPlan

        srv = _OneShotServer(["PONG\n"])
        try:
            client = TokenClient("127.0.0.1", srv.port, "ns/pod-a",
                                 max_retries=3)
            client.fault_clock = FaultClock(
                FaultPlan(seed=3).refuse_tokend(0))
            assert client._round_trip("PING ns/pod-a\n") == "PONG"
            assert client.retry_counts == {
                "retried": 1, "recovered": 1, "exhausted": 0}
            fams = client.collect_metrics()
            assert _metric(fams, "kubeshare_tokend_retries_total",
                           outcome="recovered") == 1
            # the refusal burned virtual, not wall, time
            assert client.fault_clock.now() > 0
        finally:
            srv.close()

    def test_permanent_failure_still_raises_after_bounded_attempts(self):
        from kubeshare_tpu.isolation.client import TokenClient

        client = TokenClient("127.0.0.1", 1, "ns/pod-a", max_retries=2)
        client.BACKOFF_BASE_S = 0.001  # keep the test fast
        with pytest.raises(ConnectionError, match="unreachable after 3"):
            client._round_trip("PING ns/pod-a\n")
        assert client.retry_counts["exhausted"] == 1
        assert client.retry_counts["retried"] == 2

    def test_backoff_is_bounded_exponential_with_deterministic_jitter(self):
        from kubeshare_tpu.isolation.client import TokenClient

        a = TokenClient("127.0.0.1", 1, "ns/pod-a")
        b = TokenClient("127.0.0.1", 1, "ns/pod-b")
        sched_a = [a._backoff_s(k) for k in range(8)]
        # deterministic: same pod, same schedule
        assert sched_a == [a._backoff_s(k) for k in range(8)]
        # jittered: different pods don't sync their storms
        assert sched_a != [b._backoff_s(k) for k in range(8)]
        # bounded: jitter is +/-25% around an exponential, capped
        for k, s in enumerate(sched_a):
            base = min(a.BACKOFF_CAP_S, a.BACKOFF_BASE_S * (2 ** k))
            assert 0.75 * base <= s <= 1.25 * base
        assert sched_a[-1] <= 1.25 * a.BACKOFF_CAP_S

    def test_max_retries_validation(self):
        from kubeshare_tpu.isolation.client import TokenClient

        with pytest.raises(ValueError):
            TokenClient("127.0.0.1", 1, "ns/pod-a", max_retries=-1)


class TestFabricChaos:
    """The fabric's chaos seams: seeded frame drop / duplicate /
    reorder / corruption across the cluster KV fabric, and rotten disk
    sectors under the DISK tier — every fault is absorbed by the
    at-least-once redelivery contract (or the crc) and the streams stay
    BIT-EXACT with the fault-free arm."""

    def test_fabric_builders_validate_and_chain(self):
        from kubeshare_tpu.serving.chaos import FaultPlan

        plan = (FaultPlan(seed=9).drop_fabric(0).duplicate_fabric(2)
                .reorder_fabric(4).corrupt_fabric(6)
                .corrupt_disk_read(1))
        assert plan.fabric_drops == {0}
        assert plan.fabric_duplicates == {2}
        assert plan.fabric_reorders == {4}
        assert plan.fabric_corruptions == {6}
        assert plan.disk_corruptions == {1}
        for bad in (lambda p: p.drop_fabric(-1),
                    lambda p: p.duplicate_fabric(-1),
                    lambda p: p.reorder_fabric(-1),
                    lambda p: p.corrupt_fabric(-1),
                    lambda p: p.corrupt_disk_read(-1)):
            with pytest.raises(ValueError):
                bad(FaultPlan())

    def test_fabric_transmit_faults_are_seeded_and_deterministic(self):
        """Replay determinism at the seam: the same plan mutates the
        same frame the same way; a different seed flips a different
        bit."""
        from kubeshare_tpu.serving.chaos import FaultClock, FaultPlan

        frame = bytes(range(64)) * 3

        def run(seed):
            clock = FaultClock(FaultPlan(seed=seed).corrupt_fabric(0))
            return clock.on_fabric_transmit(frame)

        a, b, c = run(3), run(3), run(4)
        assert a == b and a != c
        assert len(a) == 1 and len(a[0][0]) == len(frame)
        clock = FaultClock(FaultPlan(seed=3).drop_fabric(0)
                           .duplicate_fabric(1).reorder_fabric(2))
        assert clock.on_fabric_transmit(frame) == []
        assert clock.on_fabric_transmit(frame) == [(frame, False),
                                                   (frame, False)]
        assert clock.on_fabric_transmit(frame) == [(frame, True)]
        assert clock.on_fabric_transmit(frame) == [(frame, False)]
        kinds = [e[0] for e in clock.events]
        assert kinds == ["drop_fabric", "duplicate_fabric",
                         "reorder_fabric"]

    def test_fleet_drain_over_faulty_fabric_bit_exact(self):
        """Drain inheritance over a fabric losing, duplicating,
        reordering AND corrupting frames: redelivery recovers every
        chain, the survivor still inherits the retiree's prefix, the
        streams equal the fault-free fleet's, and the send-side
        counters reconcile (delivered + expired == sent, nothing in
        flight)."""
        from kubeshare_tpu.serving import Request
        from kubeshare_tpu.serving.chaos import FaultClock, FaultPlan
        from kubeshare_tpu.serving.fabric import LoopbackTransport

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)

        def run(clock):
            fleet = _fleet(params, config, shared_tier_bytes=1 << 20,
                           fault_clock=clock,
                           fabric=LoopbackTransport(),
                           fabric_ttl_ticks=12)
            fleet.warmup()
            rng = np.random.default_rng(11)
            shared = rng.integers(0, 64, 16)

            def req(rid):
                return Request(rid, np.concatenate(
                    [shared, rng.integers(0, 64, 4)]), 4)

            streams = {}
            fleet.submit(req("seed"))
            streams.update(
                {r: o.tokens for r, o in fleet.run().items()})
            owner = fleet.owner_of("seed")
            fleet.drain(owner)
            fleet.run()
            fleet.submit(req("heir"))
            streams.update(
                {r: o.tokens for r, o in fleet.run().items()})
            return fleet, streams

        plan = FaultPlan(seed=21)
        # rough the early frames up: ordinals count EVERY transmit
        # (data, acks, redeliveries), so this hits a mix of both
        for n in (0, 5):
            plan.drop_fabric(n)
        plan.corrupt_fabric(2).duplicate_fabric(3).reorder_fabric(7)
        clock = FaultClock(plan)
        chaotic, got = run(clock)
        _, want = run(None)
        assert got == want  # bit-exact with the fault-free arm
        faults = {e[0] for e in clock.events}
        assert "drop_fabric" in faults and "corrupt_fabric" in faults
        eps = list(chaotic._endpoints.values()) + [chaotic._fleet_ep]
        assert all(ep.inflight == 0 for ep in eps)
        sent = sum(ep.messages.get(("chain", "sent"), 0) for ep in eps)
        delivered = sum(ep.messages.get(("chain", "delivered"), 0)
                        for ep in eps)
        expired = sum(ep.messages.get(("chain", "expired"), 0)
                      for ep in eps)
        assert sent > 0 and delivered + expired == sent
        assert sum(ep.redeliveries for ep in eps) > 0
        fams = chaotic.collect_metrics()
        assert _metric(fams,
                       "kubeshare_serving_fabric_redeliveries_total") > 0
        # the survivor still inherited the retiree's prefix
        assert chaotic.fabric_adopted_tokens > 0

    def test_disagg_tickets_over_faulty_fabric_bit_exact(self):
        """Handoff tickets through a lossy fabric: a dropped ticket
        frame redelivers under backoff, a dropped ACK dedups on the
        decode side, and the split-pool streams still equal the
        monolithic engine's token for token."""
        from kubeshare_tpu.serving import (DisaggRouter, EngineConfig,
                                           Request, ServingEngine)
        from kubeshare_tpu.serving.chaos import FaultClock, FaultPlan
        from kubeshare_tpu.serving.fabric import LoopbackTransport

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)

        def reqs():
            return [Request(
                f"r{i}", np.arange(3 + i * 2) % 60, 8,
                temperature=(0.0 if i % 2 else 0.7),
                rng=(None if i % 2 else jax.random.PRNGKey(100 + i)))
                for i in range(5)]

        mono = ServingEngine(params, config, EngineConfig(
            num_slots=3, block_size=4, num_blocks=41,
            max_request_len=48, prefill_chunk=8, mixed=False))
        for r in reqs():
            mono.submit(r)
        want = {rid: res.tokens for rid, res in mono.run().items()}

        plan = (FaultPlan(seed=31).drop_fabric(0).drop_fabric(3)
                .duplicate_fabric(5).corrupt_fabric(7))
        clock = FaultClock(plan)
        fabric = LoopbackTransport()
        fabric.fault_clock = clock
        router = DisaggRouter(
            params, config,
            EngineConfig(num_slots=2, block_size=4, num_blocks=17,
                         max_request_len=48, prefill_chunk=8,
                         mixed=False),
            EngineConfig(num_slots=3, block_size=4, num_blocks=25,
                         max_request_len=48, prefill_chunk=8,
                         mixed=False),
            fabric=fabric, fabric_ttl_ticks=12)
        for r in reqs():
            router.submit(r)
        got = {rid: res.tokens for rid, res in router.run().items()}
        assert got == want
        assert clock.events  # the plan actually fired
        assert router._fabric_inflight == {}
        assert router._fabric_arrivals == []
        pf, dc = router._fabric_pf, router._fabric_dc
        assert pf.inflight == 0
        assert (pf.messages.get(("ticket", "delivered"), 0)
                + pf.messages.get(("ticket", "expired"), 0)
                == pf.messages[("ticket", "sent")])
        assert pf.redeliveries + dc.redeliveries > 0

    def test_disk_rot_is_a_loud_miss_not_wrong_tokens(self):
        """Rot EVERY disk sector read: each staged promotion detects
        the flip (block crc), drops the node's subtree, and the request
        re-prefills cold — the stream equals the dense reference, and
        the corruption is counted on the metrics plane."""
        from kubeshare_tpu.models.decoding import greedy_decode
        from kubeshare_tpu.serving import (EngineConfig, Request,
                                           ServingEngine,
                                           wire_block_bytes)
        from kubeshare_tpu.serving.chaos import FaultClock, FaultPlan

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        full_wire = wire_block_bytes(4, config.n_layers, config.kv_heads,
                                     4, config.head_dim, 4)
        engine = ServingEngine(params, config, EngineConfig(
            num_slots=1, block_size=4, num_blocks=13,
            max_request_len=32, prefill_chunk=8,
            host_tier_bytes=3 * full_wire, disk_tier_bytes=1 << 20))
        plan = FaultPlan(seed=23)
        for n in range(200):
            plan.corrupt_disk_read(n)
        engine.disk_tier.fault_clock = FaultClock(plan)
        rng = np.random.default_rng(11)
        shared = rng.integers(0, 64, 13)
        for rid, prompt in (("r0", shared),
                            ("f1", rng.integers(0, 64, 29)),
                            ("f2", rng.integers(0, 64, 29))):
            engine.submit(Request(rid, prompt, 3))
            engine.run()
            engine.pop_finished()
        assert engine.disk_tier.stored_blocks > 0
        hit = np.concatenate([shared, rng.integers(0, 64, 4)])
        engine.submit(Request("hit", hit, 3))
        out = engine.run()
        ref = np.asarray(greedy_decode(
            params, config, jnp.asarray(hit, jnp.int32)[None], 3))[0]
        assert out["hit"].tokens == list(ref)
        assert engine.disk_tier.corrupt_reads > 0
        fams = engine.collect_metrics()
        assert _metric(fams,
                       "kubeshare_serving_disk_tier_blocks_total",
                       event="corrupt_read") > 0
