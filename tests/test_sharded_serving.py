"""Tensor-parallel sharded serving tests (serving/sharded.py).

The contract under test is the ISSUE's acceptance bar: on a forced
multi-device CPU mesh, a sharded engine's streams are BIT-IDENTICAL to
the single-device engine's — greedy and sampled, GQA/windowed/MoE,
through prefix-cache hits, CoW divergence, preemption-resume, tiering
round-trips, and speculation — with zero recompiles after warmup.  Plus
the strict-mesh satellite: ``MeshSpec.resolve`` rejects degenerate
specs loudly and ``serving_mesh`` builds the serving preset.

Workload geometries deliberately mirror tests/test_serving.py's (same
prompts, same PRNG seeds, same engine shapes) so the single-device
references hit the persistent compile cache instead of compiling anew.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeshare_tpu.models.transformer import TransformerConfig, transformer_init
from kubeshare_tpu.parallel.mesh import MeshSpec, serving_mesh

pytestmark = pytest.mark.serving

TP = 4
TP_SPEC = MeshSpec(dp=1, tp=TP, sp=1)
needs_mesh = pytest.mark.skipif(
    len(jax.devices()) < TP,
    reason=f"needs {TP} devices (conftest forces 8 CPU devices)")


def _small_config(**extra):
    return TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=64, dtype=jnp.float32, attention="reference", **extra)


def _sharded_engine(params, config, **overrides):
    from kubeshare_tpu.serving import EngineConfig, ServingEngine

    kwargs = dict(num_slots=3, block_size=4, num_blocks=41,
                  max_request_len=48, prefill_chunk=8, mesh_spec=TP_SPEC)
    kwargs.update(overrides)
    return ServingEngine(params, config, EngineConfig(**kwargs))


def _run_sequentially(engine, reqs):
    from kubeshare_tpu.serving import Request

    out = {}
    for req in reqs:
        engine.submit(Request(**req))
        out.update({rid: r.tokens for rid, r in engine.run().items()
                    if r.done})
        engine.pop_finished()
    return out


class TestServingMeshStrict:
    """Satellite: ``MeshSpec.resolve`` fails loudly on every degenerate
    spec (zero axes, ambiguous fills, wrong products) and the
    ``serving_mesh`` preset builds the dp x tp serving shape."""

    def test_zero_axis_is_loud(self):
        with pytest.raises(ValueError, match="degenerate"):
            MeshSpec(dp=0, tp=1).resolve(4)
        with pytest.raises(ValueError, match="degenerate"):
            MeshSpec(tp=-2).resolve(4)

    def test_multiple_fill_axes_are_ambiguous(self):
        with pytest.raises(ValueError, match="ambiguous"):
            MeshSpec(dp=-1, tp=-1).resolve(8)

    def test_wrong_product_is_loud(self):
        # over-subscribed (the old code silently truncated devices)
        with pytest.raises(ValueError, match="spans 6 devices"):
            MeshSpec(dp=2, tp=3).resolve(4)
        # under-subscribed
        with pytest.raises(ValueError, match="spans 2 devices"):
            MeshSpec(dp=1, tp=2).resolve(8)
        # fill axis that cannot absorb evenly
        with pytest.raises(ValueError, match="multiple of 3"):
            MeshSpec(dp=-1, tp=3).resolve(8)

    def test_valid_specs_resolve(self):
        assert MeshSpec(dp=-1, tp=2).resolve(8) == (4, 1, 2, 1)
        assert MeshSpec(dp=2, tp=2, sp=2).resolve(8) == (2, 1, 2, 2)
        assert MeshSpec(dp=1, tp=1).resolve(1) == (1, 1, 1, 1)

    @needs_mesh
    def test_serving_mesh_preset(self):
        mesh = serving_mesh(TP)
        assert dict(mesh.shape) == {"dp": 1, "tp": TP, "sp": 1}
        # uses the LEADING tp devices, even when more are available
        assert list(mesh.devices.flat) == jax.devices()[:TP]

    def test_serving_mesh_validation_is_loud(self):
        with pytest.raises(ValueError, match="tp >= 1"):
            serving_mesh(0)
        n = len(jax.devices())
        with pytest.raises(ValueError, match=f"only {n} available"):
            serving_mesh(n + 1)


class TestShardingPlan:
    """The tri-state sharding decision: head-sharded when KV heads
    divide tp, replicated-KV fallback when there are fewer KV heads
    than devices, a loud error for indivisible splits — and MoE expert
    weights always replicated (expert psums would break the
    no-partial-sums bit-exactness rule)."""

    def test_head_sharded_when_divisible(self):
        from kubeshare_tpu.serving import plan_sharding

        dec = plan_sharding(_small_config(), TP)
        assert dec.attn_sharded and dec.mlp_sharded and dec.lm_head_sharded

    def test_replicated_fallback_when_kv_heads_below_tp(self):
        from kubeshare_tpu.serving import plan_sharding

        dec = plan_sharding(
            _small_config(n_kv_heads=2, positional="rope"), TP)
        assert not dec.attn_sharded
        assert dec.mlp_sharded  # the MLP halves still shard

    def test_indivisible_kv_heads_is_loud(self):
        from kubeshare_tpu.serving import plan_sharding

        config = TransformerConfig(
            vocab_size=64, d_model=48, n_heads=12, n_layers=2, d_ff=64,
            max_seq_len=64, dtype=jnp.float32, attention="reference",
            n_kv_heads=6)
        with pytest.raises(ValueError, match="not divisible by tp=4"):
            plan_sharding(config, TP)

    def test_indivisible_d_ff_is_loud(self):
        from kubeshare_tpu.serving import plan_sharding

        config = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=66,
            max_seq_len=64, dtype=jnp.float32, attention="reference")
        with pytest.raises(ValueError, match="d_ff 66"):
            plan_sharding(config, TP)

    def test_indivisible_vocab_falls_back_to_replicated_lm_head(self):
        from kubeshare_tpu.serving import plan_sharding

        config = TransformerConfig(
            vocab_size=63, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq_len=64, dtype=jnp.float32, attention="reference")
        dec = plan_sharding(config, TP)
        assert dec.attn_sharded and not dec.lm_head_sharded

    def test_moe_experts_stay_replicated(self):
        from jax.sharding import PartitionSpec as P

        from kubeshare_tpu.parallel.mesh import param_spec_tree
        from kubeshare_tpu.serving import (plan_sharding,
                                           serving_sharding_rules)

        config = _small_config(moe_every=2, moe_num_experts=4, moe_top_k=2)
        params = transformer_init(jax.random.PRNGKey(0), config)
        rules = serving_sharding_rules(plan_sharding(config, TP))
        specs = param_spec_tree(params, rules)
        # layer 0 is dense: its MLP shards; layer 1 is MoE: replicated
        assert specs["layers"][0]["mlp"]["w_in"] == P(None, "tp")
        assert specs["layers"][1]["moe"]["w_in"] == P()
        assert specs["layers"][1]["moe"]["w_out"] == P()


@needs_mesh
class TestShardedServing:
    """The acceptance suite: sharded streams bit-identical to the
    single-device engine on the forced 4-device CPU mesh, across every
    engine property PRs 1-9 locked."""

    def test_greedy_streams_match_single_device_across_configs(self):
        """Engine vs engine, token for token — MHA (head-sharded),
        GQA+RoPE (kv_heads < tp: the replicated-KV fallback), windowed,
        and MoE (replicated experts)."""
        from kubeshare_tpu.serving import EngineConfig, Request, ServingEngine

        cases = {
            "mha": dict(),
            "gqa_rope": dict(n_kv_heads=2, positional="rope"),
            "windowed": dict(attention_window=6),
            "moe": dict(moe_every=2, moe_num_experts=4, moe_top_k=2),
        }
        base = dict(num_slots=3, block_size=4, num_blocks=41,
                    max_request_len=48, prefill_chunk=8)
        for name, extra in cases.items():
            config = _small_config(**extra)
            params = transformer_init(jax.random.PRNGKey(0), config)
            prompt = np.asarray(jax.random.randint(
                jax.random.PRNGKey(1), (13,), 0, 64), np.int32)
            single = ServingEngine(params, config, EngineConfig(**base))
            single.submit(Request("r0", prompt, 8))
            want = single.run()["r0"].tokens
            sharded = _sharded_engine(params, config)
            sharded.submit(Request("r0", prompt, 8))
            got = sharded.run()["r0"].tokens
            assert got == want, name
            expect_fallback = name == "gqa_rope"  # 2 KV heads < tp=4
            assert sharded._sharded.decision.attn_sharded != \
                expect_fallback, name

    def test_replicated_fallback_pool_and_params_stay_replicated(self):
        """kv_heads < tp: the pool and the attention weights replicate
        (sharding them is impossible without breaking GQA groups); the
        MLP halves still shard."""
        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        engine = _sharded_engine(params, config)
        assert engine.pool.k.sharding.is_fully_replicated
        assert engine.params["layers"][0]["attn"][
            "wq"].sharding.is_fully_replicated
        assert not engine.params["layers"][0]["mlp"][
            "w_in"].sharding.is_fully_replicated

    def test_head_sharded_pool_splits_kv_head_axis(self):
        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        engine = _sharded_engine(params, config)
        assert not engine.pool.k.sharding.is_fully_replicated
        # axis 2 of [n_layers, num_blocks, kv_heads, bs, head_dim]
        shard = next(iter(engine.pool.k.addressable_shards))
        assert shard.data.shape[2] == config.kv_heads // TP

    def test_indivisible_kv_heads_is_loud_at_engine_build(self):
        config = TransformerConfig(
            vocab_size=64, d_model=48, n_heads=12, n_layers=2, d_ff=64,
            max_seq_len=64, dtype=jnp.float32, attention="reference",
            n_kv_heads=6)
        params = transformer_init(jax.random.PRNGKey(0), config)
        with pytest.raises(ValueError, match="not divisible by tp=4"):
            _sharded_engine(params, config)

    def test_sampled_stream_matches_dense_oracle(self):
        """Same rng => the SHARDED engine reproduces the dense sampled
        oracle exactly (the single-device engine's locked contract,
        inherited bit-for-bit)."""
        from kubeshare_tpu.models.decoding import sample_decode
        from kubeshare_tpu.serving import Request

        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        prompt = np.asarray(jax.random.randint(
            jax.random.PRNGKey(1), (10,), 0, 64), np.int32)
        rng = jax.random.PRNGKey(7)
        dense = np.asarray(sample_decode(
            params, config, jnp.asarray(prompt)[None], rng, 6,
            temperature=0.8, top_k=10, top_p=0.95))[0]
        engine = _sharded_engine(params, config, top_k=10, top_p=0.95)
        engine.submit(Request("r0", prompt, 6, temperature=0.8, rng=rng))
        assert engine.run()["r0"].tokens == list(dense)

    def test_zero_recompiles_after_warmup(self):
        """The acceptance bar's compile lock: warmup under the mesh
        compiles every dispatchable shape ONCE; a mixed-length workload
        (mid-flight admissions, ragged tails, CoW) adds zero."""
        from kubeshare_tpu.serving import Request

        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        engine = _sharded_engine(params, config)
        engine.warmup()
        baseline = engine.compile_counts()
        rng = np.random.default_rng(3)
        shapes = [(1, 3), (5, 8), (13, 4), (21, 11), (29, 2)]
        for i, (length, new) in enumerate(shapes):
            engine.submit(Request(f"r{i}", rng.integers(0, 64, length),
                                  new))
        engine.run()
        assert engine.compile_counts() == baseline

    def test_device_loop_sharded_bit_exact(self):
        """The device-resident multi-step loop under tp: the while-loop
        and its collectives live inside ONE shard_map program (the cond
        reads only replicated values, so every device runs the same
        unit count) and the sharded K=4 engine emits EXACTLY the
        single-device K=1 streams — greedy and sampled — with the same
        ~K x planner-invocation drop and zero recompiles after warmup,
        ``compile_counts()[\"loop\"]`` included."""
        from kubeshare_tpu.serving import EngineConfig, Request, ServingEngine

        config = _small_config()  # 4 KV heads: head-sharded on tp=4
        params = transformer_init(jax.random.PRNGKey(0), config)
        base = dict(num_slots=3, block_size=4, num_blocks=41,
                    max_request_len=48, prefill_chunk=8,
                    top_k=10, top_p=0.95)
        rng = np.random.default_rng(9)
        reqs = [
            dict(rid="d", prompt=rng.integers(0, 64, 5),
                 max_new_tokens=24),
            dict(rid="s", prompt=rng.integers(0, 64, 13),
                 max_new_tokens=9, temperature=0.8,
                 rng=jax.random.PRNGKey(10)),
        ]
        single = ServingEngine(params, config, EngineConfig(**base))
        for req in reqs:
            single.submit(Request(**req))
        want = {rid: r.tokens for rid, r in single.run().items()}

        engine = _sharded_engine(params, config, steps_per_launch=4,
                                 top_k=10, top_p=0.95)
        engine.warmup()
        baseline = engine.compile_counts()
        assert baseline["loop"] >= 1
        for req in reqs:
            engine.submit(Request(**req))
        got = {rid: r.tokens for rid, r in engine.run().items()}
        assert got == want
        assert engine.loop_launches >= 1
        assert engine.host_planner_invocations < \
            single.host_planner_invocations
        assert engine.compile_counts() == baseline

    def test_cow_divergence_sharded(self):
        """Sharded CoW: a mid-block divergence copies the shared tail
        block through the shard_map copy twin, and neither the
        diverging stream nor the original's replay changes."""
        from kubeshare_tpu.models.decoding import greedy_decode

        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        rng = np.random.default_rng(4)
        a = rng.integers(0, 64, 10)
        b = a.copy()
        b[9] = (b[9] + 7) % 64  # diverges at the tail block's 2nd row
        engine = _sharded_engine(params, config)
        got = _run_sequentially(engine, [
            dict(rid="a1", prompt=a, max_new_tokens=6),
            dict(rid="b", prompt=b, max_new_tokens=6),
            dict(rid="a2", prompt=a.copy(), max_new_tokens=6),
        ])
        assert engine.cow_copies >= 1
        assert engine.prefix_hit_requests >= 1  # a2 resumed off a1's blocks
        for rid, prompt in (("a1", a), ("b", b), ("a2", a)):
            ref = np.asarray(greedy_decode(
                params, config, jnp.asarray(prompt, jnp.int32)[None], 6))[0]
            assert got[rid] == list(ref), rid
        assert got["a1"] == got["a2"]

    def test_prefix_hit_sampled_sharded(self):
        """The key schedule survives a prefix-cache hit under the mesh:
        a sampled request admitted onto a matched prefix reproduces its
        solo dense stream."""
        from kubeshare_tpu.models.decoding import sample_decode

        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        prompt = np.asarray(jax.random.randint(
            jax.random.PRNGKey(3), (14,), 0, 64), np.int32)
        rng = jax.random.PRNGKey(9)
        engine = _sharded_engine(params, config, top_k=10, top_p=0.95)
        got = _run_sequentially(engine, [
            dict(rid="warm", prompt=prompt, max_new_tokens=3),
            dict(rid="samp", prompt=prompt.copy(), max_new_tokens=5,
                 temperature=0.8, rng=rng),
        ])
        assert engine.prefix_hit_tokens == 13
        ref = np.asarray(sample_decode(
            params, config, jnp.asarray(prompt)[None], rng, 5,
            temperature=0.8, top_k=10, top_p=0.95))[0]
        assert got["samp"] == list(ref)

    def test_preemption_resume_sharded_bit_exact(self):
        """QoS preemption under the mesh: the Opportunistic victim's
        blocks retire into the (sharded) prefix cache and the resume
        emits exactly its unpreempted stream."""
        from kubeshare_tpu.models.decoding import greedy_decode
        from kubeshare_tpu.serving import (QOS_OPPORTUNISTIC, EngineConfig,
                                           Request, ServingEngine,
                                           TenantRegistry, TenantSpec)

        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        registry = TenantRegistry([
            TenantSpec("gold"),
            TenantSpec("batch", qos_class=QOS_OPPORTUNISTIC),
        ])
        engine = ServingEngine(
            params, config,
            EngineConfig(num_slots=2, block_size=4, num_blocks=13,
                         max_request_len=32, prefill_chunk=8,
                         mesh_spec=TP_SPEC),
            tenants=registry)
        rng = np.random.default_rng(21)
        p_batch = rng.integers(0, 64, 17)  # 17 + 14 = 31 rows -> 8 blocks
        p_gold = rng.integers(0, 64, 18)   # 18 + 6 = 24 rows -> 6 blocks
        engine.submit(Request("victim", p_batch, 14, tenant="batch"))
        while True:  # drive the victim mid-decode before gold arrives
            slots = [s for s in engine._slots if s.rid == "victim"
                     and s.state == "decode"]
            if slots and len(slots[0].generated) >= 2:
                break
            assert engine.step(), "engine idle before victim decoded"
        engine.submit(Request("gold", p_gold, 6, tenant="gold"))
        out = engine.run()
        assert engine.preemptions.get("batch", 0) >= 1
        for rid, prompt, new in (("victim", p_batch, 14),
                                 ("gold", p_gold, 6)):
            ref = np.asarray(greedy_decode(
                params, config, jnp.asarray(prompt, jnp.int32)[None],
                new))[0]
            assert out[rid].tokens == list(ref), rid
        assert engine.prefix_hit_requests >= 1

    def test_speculative_sharded_bit_exact(self):
        """Speculation under the mesh: verify chunks run through the
        shard_map twin and the streams stay the non-speculative (and
        dense-oracle) streams exactly."""
        from kubeshare_tpu.models.decoding import greedy_decode
        from kubeshare_tpu.serving import Request

        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        rng = np.random.default_rng(52)
        base = rng.integers(0, 64, 6)
        # repetitive prompts (the traffic speculation exists for) plus
        # an incompressible control lane riding verify at width 1
        reqs = [
            dict(rid="rep0", prompt=np.tile(base, 4)[:22],
                 max_new_tokens=10),
            dict(rid="rep1", prompt=np.tile(rng.integers(0, 64, 4),
                                            5)[:17], max_new_tokens=8),
            dict(rid="rand", prompt=rng.integers(0, 64, 9),
                 max_new_tokens=6),
        ]
        engine = _sharded_engine(params, config, speculative=True,
                                 draft_len=4)
        for req in reqs:
            engine.submit(Request(**req))
        got = {rid: r.tokens for rid, r in engine.run().items()}
        for req in reqs:
            ref = np.asarray(greedy_decode(
                params, config,
                jnp.asarray(req["prompt"], jnp.int32)[None],
                req["max_new_tokens"]))[0]
            assert got[req["rid"]] == list(ref), req["rid"]
        assert engine.verify_steps > 0
        assert sum(engine.spec_drafted.values()) > 0

    def test_spec_loop_sharded_bit_exact(self):
        """Device residency v2 under the mesh: verify-in-loop launches
        (with the admission ring armed) run through the shard_map twin
        — the loop cond gathers logits so every device computes
        identical picks, alive masks and ring heads — and the streams
        are BIT-IDENTICAL to the single-device non-loop speculative
        engine's, greedy AND sampled, zero recompiles after warmup."""
        from kubeshare_tpu.serving import (EngineConfig, Request,
                                           ServingEngine)

        config = _small_config(n_kv_heads=2, positional="rope")
        params = transformer_init(jax.random.PRNGKey(0), config)
        rng = np.random.default_rng(57)
        reqs = []
        for i in range(5):
            pat = rng.integers(0, 64, 4)
            prompt = np.concatenate([np.tile(pat, 3),
                                     rng.integers(0, 64, 2)])
            req = dict(rid=f"r{i}", prompt=prompt, max_new_tokens=9)
            if i in (1, 3):
                req.update(temperature=0.8,
                           rng=jax.random.PRNGKey(58 + i))
            reqs.append(req)
        kwargs = dict(speculative=True, draft_len=4, top_k=10,
                      top_p=0.95)
        engine = _sharded_engine(params, config, steps_per_launch=4,
                                 admission_ring=2, **kwargs)
        engine.warmup()
        baseline = engine.compile_counts()
        assert baseline["spec_loop"] >= 1
        for req in reqs:
            engine.submit(Request(**req))
        got = {rid: r.tokens for rid, r in engine.run().items()}
        oracle = ServingEngine(params, config, EngineConfig(
            num_slots=3, block_size=4, num_blocks=41,
            max_request_len=48, prefill_chunk=8, **kwargs))
        for req in reqs:
            oracle.submit(Request(**req))
        want = {rid: r.tokens for rid, r in oracle.run().items()}
        assert got == want
        assert engine.spec_loop_launches > 0
        assert engine.spec_loop_units > 0
        assert engine.compile_counts() == baseline

    def test_long_context_threshold_routes_bit_exact(self):
        """Past the threshold, prefill chunks re-shard Ulysses-style
        (sequence-parallel attention inside the program) — and the
        stream does not move by a bit."""
        from kubeshare_tpu.models.decoding import greedy_decode
        from kubeshare_tpu.serving import Request

        config = _small_config()  # 4 KV heads: head-sharded
        params = transformer_init(jax.random.PRNGKey(0), config)
        prompt = np.asarray(jax.random.randint(
            jax.random.PRNGKey(1), (21,), 0, 64), np.int32)
        ref = np.asarray(greedy_decode(
            params, config, jnp.asarray(prompt)[None], 6))[0]
        # two full 8-wide chunks route through Ulysses; the ragged
        # 5-wide tail stays head-parallel (below the threshold)
        engine = _sharded_engine(params, config,
                                 long_context_threshold=8)
        engine.submit(Request("r0", prompt, 6))
        assert engine.run()["r0"].tokens == list(ref)
        assert engine._sharded.decision.attn_sharded

    def test_long_context_threshold_requires_mesh(self):
        from kubeshare_tpu.serving import EngineConfig, ServingEngine

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        with pytest.raises(ValueError, match="requires mesh_spec"):
            ServingEngine(params, config, EngineConfig(
                num_slots=3, block_size=4, num_blocks=41,
                max_request_len=48, prefill_chunk=8,
                long_context_threshold=8))

    def test_tier_roundtrip_sharded(self):
        """KV tiering under the mesh: demotion gathers sharded blocks
        to host wire bytes, promotion re-scatters them through the
        sharded upload twin — streams stay the dense oracle's."""
        from kubeshare_tpu.models.decoding import greedy_decode
        from kubeshare_tpu.serving import Request

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        engine = _sharded_engine(params, config, num_slots=1,
                                 num_blocks=13, max_request_len=32,
                                 host_tier_bytes=1 << 20)
        rng = np.random.default_rng(11)
        shared = rng.integers(0, 64, 13)
        reqs = [
            dict(rid="r0", prompt=shared, max_new_tokens=3),
            dict(rid="f1", prompt=rng.integers(0, 64, 29),
                 max_new_tokens=3),
            dict(rid="f2", prompt=rng.integers(0, 64, 29),
                 max_new_tokens=3),
            dict(rid="hit", prompt=np.concatenate(
                [shared, rng.integers(0, 64, 4)]), max_new_tokens=3),
        ]
        got = _run_sequentially(engine, reqs)
        assert engine.tier_demoted_blocks > 0
        assert engine.tier_promoted_blocks > 0
        assert engine.tier_hit_requests > 0
        for req in reqs:
            ref = np.asarray(greedy_decode(
                params, config,
                jnp.asarray(req["prompt"], jnp.int32)[None],
                req["max_new_tokens"]))[0]
            assert got[req["rid"]] == list(ref), req["rid"]

    def test_collective_bytes_counter_and_tp_label(self):
        """Satellite: the sharded engine's dispatch families carry the
        tp constant-label and the collective-bytes counter accumulates
        from shard shapes; a single-device engine exports neither."""
        from kubeshare_tpu.serving import EngineConfig, Request, ServingEngine
        from kubeshare_tpu.utils.promtext import encode_families

        config = _small_config()
        params = transformer_init(jax.random.PRNGKey(0), config)
        engine = _sharded_engine(params, config)
        engine.submit(Request(
            "r0", np.asarray(jax.random.randint(
                jax.random.PRNGKey(1), (13,), 0, 64), np.int32), 8))
        engine.run()
        assert engine.collective_bytes["prefill_chunk"] > 0
        assert engine.collective_bytes["decode_span"] > 0
        text = encode_families(engine.collect_metrics())
        assert 'tp="4"' in text
        assert "kubeshare_serving_collective_bytes_total" in text
        plain = ServingEngine(
            params, config,
            EngineConfig(num_slots=3, block_size=4, num_blocks=41,
                         max_request_len=48, prefill_chunk=8))
        assert all(v == 0 for v in plain.collective_bytes.values())
        ptext = encode_families(plain.collect_metrics())
        assert 'tp="' not in ptext
