"""Shared helpers for tests that drive the native token runtime over TCP."""

import socket
import time


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def wait_listening(port, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port), timeout=1).close()
            return
        except OSError:
            time.sleep(0.05)
    raise TimeoutError(f"nothing listening on {port}")
