"""Shared helpers for tests that drive the native token runtime over TCP."""

import socket

from kubeshare_tpu.utils.net import wait_listening as _wait_listening


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def wait_listening(port, timeout=10.0):
    _wait_listening(port, deadline_s=timeout)
